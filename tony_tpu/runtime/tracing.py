"""Distributed tracing plane + crash flight recorder.

The metrics plane (``runtime/metrics.py``) answers "how much / how fast
on average"; this module answers "WHY was this one slow" — the causal
chain of a single request, step, or incident across processes. It rides
the same proven transports: producers record spans into a process-local
ring, the executor batches them onto heartbeats, the coordinator folds
them into ``TRACE_SPAN`` jhist events (with a per-task clock-offset
estimate applied at export), and the history server renders the job's
spans as Chrome-trace JSON (``GET /api/jobs/<id>/trace``,
Perfetto-loadable).

Design constraints (mirrors metrics.py):

- **dependency-free** — stdlib only; importable from the jax-free
  serving client, the executor, and user training processes alike;
- **cheap when off** — an unsampled span is one RNG draw and a constant
  return; a recorded span is one dict build + two deque appends. The
  bench's trace-overhead arm pins the sampled-on cost under 1 % of a
  serve chunk's wall;
- **never load-bearing** — a tracing failure (spool IO, malformed batch,
  dump error) is logged and dropped; it must never cost a heartbeat, a
  request, or a step.

Span model: 128-bit trace ids (32 hex chars), 64-bit span ids, parent
links, wall-clock start (``time.time()`` so cross-process spans align
after clock-offset correction) with ``perf_counter``-derived durations.
Head sampling: the decision is made ONCE at the trace root
(``tony.trace.sample-rate``); children — including remote children
created from a propagated context — inherit it. ``coarse=True`` roots
(job lifecycle, bring-up, incidents) bypass sampling and are always
recorded.

The flight recorder is the second leg: every process keeps a bounded
ring of recent spans + structured events; on an incident (abnormal child
exit, ``GangLostError``, a connection-scoped ``ProtocolError``) the ring
dumps to a JSON file under the job dir — a postmortem artifact instead
of only an exit code — and the executor ships the tail of its ring on
its final heartbeat so the coordinator can attach it to the incident's
jhist event.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import logging
import math
import os
import random
import re
import tempfile
import threading
import time
from collections import deque

log = logging.getLogger(__name__)

# Env plumbing (exported by the coordinator/executor; see constants.py
# for the canonical names — duplicated literally here so this module
# stays importable without the tony_tpu package root).
ENV_SPOOL = "TONY_TRACE_SPOOL"
ENV_PROC = "TONY_TRACE_PROC"
ENV_CTX = "TONY_TRACE_CTX"
ENV_SAMPLE_RATE = "TONY_TRACE_SAMPLE_RATE"
ENV_RING = "TONY_TRACE_RING"
ENV_FLIGHT_DIR = "TONY_FLIGHT_DIR"
ENV_FLIGHT_RING = "TONY_FLIGHT_RING"

#: spans shipped per heartbeat batch at most; the rest wait for the next
#: beat (the pending deque is bounded separately, so a stalled transport
#: degrades to dropped-oldest, never unbounded memory)
MAX_SPANS_PER_BATCH = 256
#: pending-ship buffer bound (per process)
DEFAULT_RING = 2048
DEFAULT_FLIGHT_RING = 256
#: flight dumps are incident artifacts, not a log stream: a flood of
#: malformed connections must not turn into a flood of files. The quota
#: is PER REASON — externally-triggerable dumps (a port scanner hitting
#: a serving port raises protocol_error repeatedly) must never starve a
#: later genuine incident's dump (gang_lost, child_exit) — with a
#: process-wide backstop.
MAX_DUMPS_PER_REASON = 4
MAX_DUMPS_PER_PROCESS = 32

_HEX_RE = re.compile(r"^[0-9a-f]{1,64}$")

_current_span: contextvars.ContextVar["Span | None"] = \
    contextvars.ContextVar("trace_current_span", default=None)

#: serializes end()'s ended-flag transition: the serve engine ends a
#: request's spans from the cancelling thread AND the engine thread in
#: the supported CANCEL-races-retirement case — a bare check-then-set
#: could record the span twice. One uncontended module lock (~100 ns)
#: beats a lock object per span.
_end_lock = threading.Lock()


# Id generation must NOT ride the global `random` module: training
# scripts routinely `random.seed(fixed)` identically on every worker,
# which would make every task emit the SAME trace/span ids and corrupt
# the folded cross-process trace. SystemRandom is urandom-backed —
# stateless, thread-safe, immune to user seeding.
_id_rng = random.SystemRandom()
# Sampling draws are cheap-path: a private auto-seeded (urandom)
# instance — unaffected by user seeding; a theoretical thread race only
# skews one sampling decision, never an id.
_sample_rng = random.Random()


def new_trace_id() -> str:
    return f"{_id_rng.getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_id_rng.getrandbits(64):016x}"


def deterministic_trace_id(seed: str) -> str:
    """128-bit trace id every party can derive from shared knowledge —
    how pipeline stage gangs agree on a per-step trace id without any
    new channel frames (seed = job trace id + step ordinal)."""
    return hashlib.sha256(seed.encode("utf-8")).hexdigest()[:32]


def deterministic_span_id(seed: str) -> str:
    return hashlib.sha256(seed.encode("utf-8")).hexdigest()[32:48]


def deterministic_sample(key: str, rate: float) -> bool:
    """Head-sampling decision every party reaches independently from
    shared knowledge — so all stages of one pipeline step record (or
    skip) the same step under partial sampling."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = int(hashlib.sha256(key.encode("utf-8")).hexdigest()[:8], 16)
    return h / float(0xFFFFFFFF) < rate


class Span:
    """One live span. End it exactly once (``end()`` or the tracer's
    context manager); attrs set after end are lost."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "ts", "_t0", "attrs", "_ended")

    recording = True

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: str, name: str, attrs: dict) -> None:
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self.attrs = attrs
        self._ended = False

    @property
    def context(self) -> dict:
        """Wire context for cross-process propagation (the ADMIT
        ``trace`` field / the ``TONY_TRACE_CTX`` env shape)."""
        return {"tid": self.trace_id, "sid": self.span_id}

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def end(self, **attrs) -> None:
        with _end_lock:
            if self._ended:
                return
            self._ended = True
        if attrs:
            self.attrs.update(attrs)
        self.tracer._finish(self, time.perf_counter() - self._t0)


class _NoopSpan:
    """Unsampled/disabled span: absorbs the API at near-zero cost and
    propagates 'not recording' to children."""

    __slots__ = ()
    recording = False
    trace_id = ""
    span_id = ""
    parent_id = ""
    context = None

    def set(self, **attrs) -> None: ...
    def end(self, **attrs) -> None: ...


NOOP_SPAN = _NoopSpan()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def parse_env_ctx(value: str | None = None) -> dict | None:
    """Parse a ``tid:sid`` env context (``TONY_TRACE_CTX``)."""
    value = value if value is not None else os.environ.get(ENV_CTX, "")
    if not value or ":" not in value:
        return None
    tid, _, sid = value.partition(":")
    if _HEX_RE.match(tid) and _HEX_RE.match(sid):
        return {"tid": tid, "sid": sid}
    return None


def format_env_ctx(ctx: dict) -> str:
    return f"{ctx['tid']}:{ctx['sid']}"


class Tracer:
    """Process-local span factory + bounded storage.

    Two deques per tracer: ``_pending`` holds finished spans awaiting
    shipment (drained onto heartbeats / jhist), ``_ring`` keeps the most
    recent spans regardless of shipment — the flight recorder's view.
    Overflowing ``_pending`` drops the OLDEST spans and counts them
    (``tony_trace_dropped_total``): under a stalled transport, recent
    causality beats ancient completeness.
    """

    def __init__(self, proc: str | None = None,
                 sample_rate: float | None = None,
                 ring_size: int | None = None,
                 spool_path: str | None = None,
                 enabled: bool = True) -> None:
        self.proc = proc if proc is not None else (
            os.environ.get(ENV_PROC) or f"pid:{os.getpid()}")
        self.sample_rate = (sample_rate if sample_rate is not None
                            else _env_float(ENV_SAMPLE_RATE, 1.0))
        self.enabled = enabled
        size = ring_size if ring_size is not None \
            else _env_int(ENV_RING, DEFAULT_RING)
        self._lock = threading.Lock()
        self._pending: deque[dict] = deque()
        self._pending_cap = max(16, size)
        self._ring: deque[dict] = deque(maxlen=max(16, size))
        self.dropped = 0
        self.recorded = 0
        self.spool_path = (spool_path if spool_path is not None
                           else os.environ.get(ENV_SPOOL) or None)
        self._spool_file = None
        self._spool_failed = False
        self._counters = None

    # -- span surface -------------------------------------------------------
    def _sampled_root(self, coarse: bool) -> bool:
        if not self.enabled:
            return False
        if coarse:
            return True
        r = self.sample_rate
        return r > 0 and (r >= 1.0 or _sample_rng.random() < r)

    def start_span(self, name: str, *, ctx: dict | None = None,
                   parent: "Span | _NoopSpan | None" = None,
                   coarse: bool = False, **attrs) -> "Span | _NoopSpan":
        """Start a span. Parent precedence: explicit ``parent`` >
        propagated wire ``ctx`` > the contextvar set by :meth:`span`.
        A remote ctx means the HEAD already sampled this trace — it is
        always recorded (head sampling)."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None and ctx is None:
            parent = _current_span.get()
        if parent is not None:
            if not parent.recording:
                return NOOP_SPAN
            return Span(self, parent.trace_id, new_span_id(),
                        parent.span_id, name, attrs)
        if ctx is not None:
            tid, sid = str(ctx.get("tid", "")), str(ctx.get("sid", ""))
            if not (_HEX_RE.match(tid) and _HEX_RE.match(sid)):
                ctx = None
            else:
                return Span(self, tid, new_span_id(), sid, name, attrs)
        if not self._sampled_root(coarse):
            return NOOP_SPAN
        return Span(self, new_trace_id(), new_span_id(), "", name, attrs)

    @contextlib.contextmanager
    def span(self, name: str, *, ctx: dict | None = None,
             coarse: bool = False, **attrs):
        """Context-manager span, parented on (and installed as) the
        ambient current span for the duration."""
        # the span itself (recording or NOOP) becomes the ambient
        # parent: an UNSAMPLED span must suppress its children too (a
        # None here would let nested spans re-roll the sampling dice as
        # orphan roots — breaking head sampling's one-decision-per-trace
        # invariant)
        sp = self.start_span(name, ctx=ctx, coarse=coarse, **attrs)
        token = _current_span.set(sp)
        try:
            yield sp
        finally:
            _current_span.reset(token)
            sp.end()

    def record_span(self, name: str, duration_s: float, *,
                    end_time: float | None = None,
                    trace_id: str | None = None,
                    span_id: str | None = None,
                    parent_id: str = "",
                    parent: "Span | _NoopSpan | None" = None,
                    ctx: dict | None = None,
                    coarse: bool = True, **attrs) -> None:
        """Record an already-finished span (bring-up timings measured by
        the backend, data-wait intervals, deterministic pipeline spans).
        Explicit ids win over ``parent``/``ctx``; with neither, the span
        roots its own trace subject to ``coarse``/sampling."""
        if not self.enabled:
            return
        if trace_id is None:
            if parent is None and ctx is None:
                parent = _current_span.get()
            if parent is not None:
                if not parent.recording:
                    return
                trace_id, parent_id = parent.trace_id, parent.span_id
            elif ctx is not None and _HEX_RE.match(str(ctx.get("tid", ""))):
                trace_id, parent_id = ctx["tid"], str(ctx.get("sid", ""))
            elif self._sampled_root(coarse):
                trace_id = new_trace_id()
            else:
                return
        end_time = time.time() if end_time is None else end_time
        self._store({
            "tid": trace_id, "sid": span_id or new_span_id(),
            "pid": parent_id, "n": name, "proc": self.proc,
            "ts": end_time - max(0.0, duration_s),
            "d": max(0.0, duration_s), "a": attrs})

    def current_context(self) -> dict | None:
        sp = _current_span.get()
        return sp.context if sp is not None and sp.recording else None

    # -- storage ------------------------------------------------------------
    def _metrics(self):
        if self._counters is None:
            from tony_tpu.runtime import metrics as metrics_mod
            reg = metrics_mod.get_default()
            self._counters = (
                reg.counter("tony_trace_spans_total",
                            help="spans recorded by this process"),
                reg.counter("tony_trace_dropped_total",
                            help="spans dropped on pending-buffer "
                                 "overflow"))
        return self._counters

    def _finish(self, span: Span, duration_s: float) -> None:
        self._store({
            "tid": span.trace_id, "sid": span.span_id,
            "pid": span.parent_id, "n": span.name, "proc": self.proc,
            "ts": span.ts, "d": duration_s, "a": span.attrs})

    def _store(self, wire: dict) -> None:
        spans_c, dropped_c = self._metrics()
        with self._lock:
            self.recorded += 1
            self._ring.append(wire)
            self._pending.append(wire)
            overflow = len(self._pending) - self._pending_cap
            for _ in range(overflow):
                self._pending.popleft()
                self.dropped += 1
        spans_c.inc()
        if overflow > 0:
            dropped_c.inc(overflow)
        if self.spool_path:
            self._spool(wire)

    def _spool(self, wire: dict) -> None:
        """Mirror finished spans to the per-task spool file the executor
        tails onto heartbeats — the bridge from the fork-exec'd user
        process to the coordinator. Best-effort: a spool error disables
        the spool (once, loudly), never the caller."""
        if self._spool_failed:
            return
        try:
            with self._lock:
                if self._spool_file is None:
                    self._spool_file = open(self.spool_path, "a",
                                            encoding="utf-8")
                self._spool_file.write(
                    json.dumps(wire, separators=(",", ":")) + "\n")
                self._spool_file.flush()
        except OSError:
            self._spool_failed = True
            log.warning("trace spool %s failed; spooling disabled",
                        self.spool_path, exc_info=True)

    def drain(self, max_spans: int = MAX_SPANS_PER_BATCH) -> list[dict]:
        """Pop up to ``max_spans`` pending spans (oldest first)."""
        out = []
        with self._lock:
            while self._pending and len(out) < max_spans:
                out.append(self._pending.popleft())
        return out

    def recent(self, n: int | None = None) -> list[dict]:
        """Most recent spans (the flight recorder's span view)."""
        with self._lock:
            spans = list(self._ring)
        return spans if n is None else spans[-n:]

    def close(self) -> None:
        with self._lock:
            if self._spool_file is not None:
                try:
                    self._spool_file.close()
                except OSError:
                    pass
                self._spool_file = None


# ---------------------------------------------------------------------------
# Wire codec + validation (heartbeat batch / jhist span payloads)
# ---------------------------------------------------------------------------
def encode_batch(spans: list[dict], flight: dict | None = None) -> str:
    """Compact heartbeat payload: ``{"s": [span...], "b": batch id,
    "f": tail?}``. The batch id lets the receiver drop a RE-DELIVERED
    batch (the heartbeat RPC retries on lost acks; span batches append
    coordinator-side, so unlike the last-snapshot metrics table a
    duplicate delivery would duplicate every span)."""
    obj: dict = {"s": spans, "b": new_span_id()}
    if flight:
        obj["f"] = flight
    return json.dumps(obj, separators=(",", ":"))


def _check_num(v, what: str) -> None:
    if isinstance(v, bool) or not isinstance(v, (int, float)) \
            or not math.isfinite(v):
        raise ValueError(f"non-finite or non-numeric {what}: {v!r}")


def validate_span(d: dict) -> dict:
    """Structural validation of one wire span; raises ValueError."""
    if not isinstance(d, dict):
        raise ValueError(f"span is not an object: {d!r}")
    for key in ("tid", "sid"):
        v = d.get(key)
        if not isinstance(v, str) or not _HEX_RE.match(v):
            raise ValueError(f"bad span {key}: {v!r}")
    pid = d.get("pid", "")
    if not isinstance(pid, str) or (pid and not _HEX_RE.match(pid)):
        raise ValueError(f"bad span pid: {pid!r}")
    if not isinstance(d.get("n"), str) or not d["n"]:
        raise ValueError(f"bad span name: {d.get('n')!r}")
    if not isinstance(d.get("proc", ""), str):
        raise ValueError(f"bad span proc: {d.get('proc')!r}")
    _check_num(d.get("ts"), "span ts")
    _check_num(d.get("d"), "span duration")
    attrs = d.get("a", {})
    if not isinstance(attrs, dict):
        raise ValueError(f"span attrs not an object: {attrs!r}")
    for k, v in attrs.items():
        if not isinstance(k, str) \
                or not isinstance(v, (str, int, float, bool)) \
                or (isinstance(v, float) and not math.isfinite(v)):
            raise ValueError(f"bad span attr {k!r}: {v!r}")
    return d


def validate_batch(obj: dict) -> dict:
    """Validate a heartbeat span batch. Raises ValueError on anything
    malformed — the coordinator drops the batch without costing the
    ping (the metrics-piggyback discipline)."""
    if not isinstance(obj, dict):
        raise ValueError("span batch is not an object")
    spans = obj.get("s", [])
    if not isinstance(spans, list) or len(spans) > 4 * MAX_SPANS_PER_BATCH:
        raise ValueError("span batch 's' is not a bounded list")
    for s in spans:
        validate_span(s)
    bid = obj.get("b", "")
    if not isinstance(bid, str) or (bid and not _HEX_RE.match(bid)):
        raise ValueError(f"span batch 'b' is not a hex id: {bid!r}")
    flight = obj.get("f")
    if flight is not None:
        if not isinstance(flight, dict) \
                or not isinstance(flight.get("events", []), list):
            raise ValueError("span batch 'f' is not a flight tail")
    return obj


def parse_batch_json(payload: str) -> dict:
    try:
        obj = json.loads(payload)
    except json.JSONDecodeError as e:
        raise ValueError(f"span batch is not JSON: {e}") from e
    return validate_batch(obj)


class SpoolReader:
    """Incremental reader over a span spool file (JSON lines appended by
    the user process's tracer). Tracks its offset, tolerates a partial
    trailing line (re-read next time) and skips malformed lines.
    :meth:`maybe_rotate` keeps the FILE bounded — the writer appends
    forever otherwise."""

    #: unread-backlog bound: past this the reader skips to EOF (recent
    #: causality beats ancient completeness) so a producer outpacing the
    #: heartbeat drain can never grow the file without bound
    MAX_BACKLOG_BYTES = 8 << 20

    def __init__(self, path: str) -> None:
        self.path = path
        self._offset = 0
        # the executor's FINAL beat (main thread) can race a still
        # in-flight periodic beat (heartbeater thread) on this reader —
        # an unsynchronized shared offset would ship spans twice or
        # rotate mid-read
        self._lock = threading.Lock()

    def maybe_rotate(self) -> None:
        """Bound the spool: fully consumed → truncate to zero (the
        writer's O_APPEND handle lands correctly at the new EOF); over
        the backlog bound → skip to EOF first, dropping the middle. A
        span appended in the tiny check-to-truncate window is lost —
        telemetry, not accounting."""
        with self._lock:
            try:
                size = os.path.getsize(self.path)
            except OSError:
                return
            if size - self._offset > self.MAX_BACKLOG_BYTES:
                log.warning("trace spool %s backlog %d bytes — skipping "
                            "to EOF", self.path, size - self._offset)
                self._offset = size
            if self._offset and self._offset >= size:
                try:
                    os.truncate(self.path, 0)
                except OSError:
                    return
                self._offset = 0

    def read_new(self, max_spans: int = MAX_SPANS_PER_BATCH) -> list[dict]:
        with self._lock:
            try:
                with open(self.path, "rb") as f:
                    f.seek(self._offset)
                    data = f.read()
            except OSError:
                return []
            if not data:
                return []
            end = data.rfind(b"\n")
            if end < 0:
                return []                  # partial first line; wait
            chunk, consumed = data[:end], end + 1
            out = []
            taken_bytes = 0
            for line in chunk.split(b"\n"):
                if len(out) >= max_spans:
                    break
                taken_bytes += len(line) + 1
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(validate_span(
                        json.loads(line.decode("utf-8"))))
                except (ValueError, UnicodeDecodeError):
                    continue               # one bad line never stalls
            self._offset += taken_bytes if len(out) >= max_spans \
                else consumed
            return out


# ---------------------------------------------------------------------------
# Clock alignment
# ---------------------------------------------------------------------------
def clock_offset(client_unix_time: float, client_rtt: float,
                 server_unix_time: float | None = None) -> float:
    """Heartbeat-RTT-midpoint skew estimate: the beat carries the
    sender's wall clock at send plus its last measured heartbeat RTT;
    under symmetric delay the send happened ``rtt/2`` before receipt,
    so ``server_now - (client_send + rtt/2)`` estimates
    ``server_clock - client_clock``. Add the offset to a task's span
    timestamps to express them on the coordinator's clock."""
    now = time.time() if server_unix_time is None else server_unix_time
    return now - (client_unix_time + max(0.0, client_rtt) / 2.0)


def apply_offset(spans: list[dict], offset_s: float) -> list[dict]:
    if not offset_s:
        return spans
    return [{**s, "ts": s["ts"] + offset_s} for s in spans]


# ---------------------------------------------------------------------------
# Chrome trace renderer (Perfetto / chrome://tracing loadable)
# ---------------------------------------------------------------------------
def to_chrome(spans: list[dict]) -> dict:
    """Render wire spans as Chrome Trace Event JSON: one ``pid`` per
    process label, one ``tid`` per (process, trace) pair — so every
    request/step gets its own track — with ``M`` metadata events naming
    both. Complete ``X`` events; timestamps in µs."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    tids: dict[tuple[int, str], int] = {}
    for s in sorted(spans, key=lambda x: x.get("ts", 0.0)):
        proc = s.get("proc") or "?"
        pid = pids.get(proc)
        if pid is None:
            pid = pids[proc] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": proc}})
        tkey = (pid, s["tid"])
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"trace {s['tid'][:8]}"}})
        args = {str(k): v for k, v in (s.get("a") or {}).items()}
        args["trace_id"] = s["tid"]
        args["span_id"] = s["sid"]
        if s.get("pid"):
            args["parent_span_id"] = s["pid"]
        events.append({
            "ph": "X", "name": s["n"],
            "cat": s["n"].split(".", 1)[0],
            "ts": round(s["ts"] * 1e6, 3),
            "dur": round(max(0.0, s["d"]) * 1e6, 3),
            "pid": pid, "tid": tid, "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Bounded ring of structured events + (via the tracer) recent
    spans; ``dump()`` writes the postmortem JSON artifact. Everything is
    best-effort by contract — recording and dumping must never raise
    into the caller."""

    def __init__(self, proc: str | None = None,
                 ring_size: int | None = None,
                 dir_path: str | None = None) -> None:
        self.proc = proc if proc is not None else (
            os.environ.get(ENV_PROC) or f"pid:{os.getpid()}")
        size = ring_size if ring_size is not None \
            else _env_int(ENV_FLIGHT_RING, DEFAULT_FLIGHT_RING)
        # default dir: explicit env (the executor exports the job dir),
        # else the system temp dir — NOT the cwd, which for bare
        # processes (tests, notebooks) is often a source tree
        self.dir_path = dir_path or os.environ.get(ENV_FLIGHT_DIR) \
            or tempfile.gettempdir()
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max(8, size))
        self._dumps = 0
        self._dumps_by_reason: dict[str, int] = {}
        self._counter = None

    def record(self, kind: str, **data) -> None:
        try:
            entry = {"ts": round(time.time(), 6), "kind": str(kind)}
            for k, v in data.items():
                if isinstance(v, (str, int, float, bool)) or v is None:
                    entry[k] = v
                else:
                    entry[k] = repr(v)[:500]
            with self._lock:
                self._ring.append(entry)
        except Exception:
            log.debug("flight record failed", exc_info=True)

    def tail(self, n: int = 32) -> list[dict]:
        with self._lock:
            entries = list(self._ring)
        return entries[-n:]

    def dump(self, reason: str, tracer: Tracer | None = None,
             path: str | None = None, **attrs) -> str | None:
        """Write the ring (+ the tracer's recent spans) as one JSON
        file; returns the path, or None on failure/over-quota. The
        final entry of every dump records the incident itself, so a
        parser can read the last entries to see what happened."""
        self.record("flight_dump", reason=reason, **attrs)
        tr = tracer if tracer is not None else get_tracer()
        with self._lock:
            by_reason = self._dumps_by_reason.get(reason, 0)
            if path is None and (self._dumps >= MAX_DUMPS_PER_PROCESS
                                 or by_reason >= MAX_DUMPS_PER_REASON):
                return None
            self._dumps += 1
            self._dumps_by_reason[reason] = by_reason + 1
            seq = self._dumps
            events = list(self._ring)
        doc = {
            "v": 1,
            "proc": self.proc,
            "reason": reason,
            "attrs": {k: v for k, v in attrs.items()
                      if isinstance(v, (str, int, float, bool))},
            "dumped_at": round(time.time(), 6),
            "pid": os.getpid(),
            "events": events,
            "spans": tr.recent(),
        }
        if path is None:
            safe = re.sub(r"[^A-Za-z0-9_.-]", "-", self.proc)
            path = os.path.join(
                self.dir_path,
                f"flight-{safe}-{os.getpid()}-{seq}.json")
        try:
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            log.warning("flight dump to %s failed", path, exc_info=True)
            return None
        if self._counter is None:
            from tony_tpu.runtime import metrics as metrics_mod
            self._counter = metrics_mod.get_default().counter(
                "tony_flight_dumps_total",
                help="flight-recorder postmortem dumps written")
        self._counter.inc()
        log.warning("flight recorder dumped to %s (reason: %s)",
                    path, reason)
        return path

    def ship_tail(self, reason: str, dump_path: str | None = None,
                  n: int = 32) -> dict:
        """The heartbeat-shippable tail: what the executor attaches to
        its final beat so the incident's jhist event carries the last
        moments even when nobody can read the host's disk."""
        return {"proc": self.proc, "reason": reason,
                "dump": dump_path or "", "events": self.tail(n)}


# ---------------------------------------------------------------------------
# Process-wide defaults
# ---------------------------------------------------------------------------
_default_tracer: Tracer | None = None
_default_flight: FlightRecorder | None = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process tracer (tests, bench contrast arms)."""
    global _default_tracer
    with _default_lock:
        prev, _default_tracer = _default_tracer, tracer
    return prev if prev is not None else tracer


def get_flight() -> FlightRecorder:
    global _default_flight
    if _default_flight is None:
        with _default_lock:
            if _default_flight is None:
                _default_flight = FlightRecorder()
    return _default_flight


def set_flight(flight: FlightRecorder) -> FlightRecorder:
    global _default_flight
    with _default_lock:
        prev, _default_flight = _default_flight, flight
    return prev if prev is not None else flight


def configure(proc: str | None = None, sample_rate: float | None = None,
              ring_size: int | None = None, spool_path: str | None = None,
              flight_dir: str | None = None,
              flight_ring: int | None = None) -> Tracer:
    """(Re)build the process tracer + flight recorder — the coordinator
    and executor call this once their config is loaded; everyone else
    inherits the env-driven defaults."""
    tracer = Tracer(proc=proc, sample_rate=sample_rate,
                    ring_size=ring_size, spool_path=spool_path)
    set_tracer(tracer)
    set_flight(FlightRecorder(proc=proc, ring_size=flight_ring,
                              dir_path=flight_dir))
    return tracer
