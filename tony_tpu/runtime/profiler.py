"""First-class profiling hooks for tony tasks.

The reference's only observability into training is TensorBoard plumbing
(SURVEY.md §5 "Tracing / profiling: ABSENT"; reference: TaskExecutor.java:
73-74,124-127 reserves a TB port and registers worker:0's URL as the YARN
tracking URL). The TPU build keeps that pattern and adds what SURVEY.md §5
calls for — per-host ``jax.profiler`` / xprof capture as a framework feature:

- ``maybe_start()``: driven by executor-exported env. When profiling is on
  (``tony.task.profile.enabled``) each host starts the jax profiler server
  on its reserved TensorBoard port, so xprof / `tensorboard --logdir` can
  capture live from the registered tracking URL. Programmatic trace files
  additionally require instrumenting the loop with :class:`StepTracer` or
  :func:`trace` (both no-ops unless ``tony.task.profile.dir`` is set).
- ``trace(logdir)``: context manager for explicit capture windows.
- ``StepTracer``: step-bounded capture — start at step A, stop at step B —
  the standard way to profile steady-state without the compile noise.
- ``PhaseTimes``: a host-side wall-clock accumulator for the phases of a
  host-driven loop (the serving batchers record ``dispatch``/``fetch``/
  ``admit``/``retire`` per :meth:`serve` call) — xprof sees device work,
  but the serving question is usually about the HOST side: how much of
  the wall went to transport syncs vs dispatch vs admission.

User scripts get all of it through ``tony_tpu.runtime.initialize()``, which
calls :func:`maybe_start` after the jax.distributed bootstrap.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time

from tony_tpu import constants

log = logging.getLogger(__name__)

_server_started = False


class PhaseTimes:
    """Wall-clock accumulator for the phases of a host-driven loop.

    Usage::

        times = PhaseTimes()
        with times.phase("dispatch"):
            handle = issue_chunk()
        with times.phase("fetch"):
            host = np.asarray(handle)
        times.total("fetch")        # seconds
        times.summary()             # {"fetch": {"total_s", "count",
                                    #            "mean_ms"}, ...}

    The serving batchers (`tony_tpu.models.serve`) keep one per
    ``serve()`` call under ``.phase_times``, recording ``dispatch``
    (building + enqueueing a device chunk — async, no device sync),
    ``fetch`` (blocking on a chunk's tokens: device compute remaining +
    the transport round trip — the time the pipelined loop overlaps with
    the next chunk), ``admit`` (admission dispatches), and ``retire``.
    Pure host timing: no jax import, no device sync of its own."""

    def __init__(self) -> None:
        self._total: dict[str, float] = {}
        self._count: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._total[name] = self._total.get(name, 0.0) + dt
            self._count[name] = self._count.get(name, 0) + 1

    def total(self, name: str) -> float:
        """Accumulated seconds in ``name`` (0.0 if never entered)."""
        return self._total.get(name, 0.0)

    def count(self, name: str) -> int:
        return self._count.get(name, 0)

    def summary(self) -> dict:
        """Per-phase {total_s, count, mean_ms}, insertion-ordered."""
        return {
            name: {"total_s": round(self._total[name], 6),
                   "count": self._count[name],
                   "mean_ms": round(
                       1e3 * self._total[name] / self._count[name], 3)}
            for name in self._total
        }


def profile_dir() -> str | None:
    """Trace output dir for this task, or None when profiling is off.
    Per-task subdir keeps multi-host captures separate."""
    base = os.environ.get(constants.TONY_PROFILE_DIR, "")
    if not base:
        return None
    job = os.environ.get(constants.JOB_NAME, "worker")
    idx = os.environ.get(constants.TASK_INDEX, "0")
    return os.path.join(base, f"{job}-{idx}")


def maybe_start() -> bool:
    """Start the per-host profiler server (idempotent) when enabled.

    Returns whether the profiler server is actually LIVE for this task —
    False when profiling is disabled, when no TB_PORT is exported (or
    it is 0), or when the server failed to start. (It used to return
    bare ``enabled``, reporting True for a task nothing could connect
    to.) Trace-file capture (:func:`trace` / :class:`StepTracer`) is
    independent of the server and keyed on ``tony.task.profile.dir``."""
    global _server_started
    enabled = os.environ.get(constants.TONY_PROFILE_ENABLED, "") == "true"
    if not enabled:
        return False
    if _server_started:
        return True
    import jax
    port = int(os.environ.get(constants.TB_PORT, "0") or "0")
    if not port:
        log.warning("profiling enabled but no TB_PORT exported — "
                    "profiler server not started")
        return False
    try:
        jax.profiler.start_server(port)
    except Exception:
        log.warning("profiler server failed to start", exc_info=True)
        return False
    _server_started = True
    log.info("jax profiler server on port %d", port)
    return True


def _reset_server_state_for_tests() -> None:
    """Forget that a profiler server was started (test isolation only —
    jax keeps its own server singleton; this resets OUR latch so
    maybe_start()'s decision logic can be exercised repeatedly)."""
    global _server_started
    _server_started = False


@contextlib.contextmanager
def trace(logdir: str | None = None):
    """Capture a jax trace for the enclosed block (xprof/TensorBoard
    viewable). Defaults to the config-shipped profile dir."""
    import jax
    logdir = logdir or profile_dir()
    if logdir is None:
        yield
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("trace written to %s", logdir)


class StepTracer:
    """Capture steps [start, stop) of a training loop::

        tracer = StepTracer(start=10, stop=13)   # skip compile+warmup
        for step in range(total):
            tracer.step(step)
            state, m = train_step(state, batch)
        tracer.close()
    """

    def __init__(self, start: int = 10, stop: int = 13,
                 logdir: str | None = None) -> None:
        self.start = start
        self.stop = stop
        self.logdir = logdir or profile_dir()
        self._active = False

    def step(self, step: int) -> None:
        if self.logdir is None:
            return
        import jax
        if not self._active and self.start <= step < self.stop:
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif self._active and step >= self.stop:
            jax.profiler.stop_trace()
            self._active = False
            log.info("step trace [%d,%d) written to %s",
                     self.start, self.stop, self.logdir)

    def close(self) -> None:
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
