"""Goodput/badput ledger: attribute every task-second to a category.

The fleet-accounting question ("of the slice-seconds we paid for, how
many produced training steps or served tokens, and where did the rest
go?") needs a time-attribution layer that metrics (point-in-time
counters) and traces (sampled spans) don't provide: an *exhaustive*
carve-up of each task's wall clock into non-overlapping categories.

Design:

- A :class:`GoodputLedger` always has exactly ONE open category (a
  stack; the base category is ``overhead``).  ``enter(cat)`` is a
  context manager that pushes a category and restores the previous one
  on exit, so "no gaps, no overlap" is structural, not something a
  caller has to get right: ``sum(categories) == now - t0`` at every
  snapshot, within float epsilon.
- Totals are *cumulative* seconds per category.  The wire snapshot that
  rides heartbeats is therefore idempotent: re-delivery or re-ingest
  after a coordinator restart rebuilds the same table (same discipline
  as the PR 2 metrics piggyback).
- The user process (trainer/server) is fork-exec'd by the executor, so
  its ledger is process-local.  It bridges via a spool file (see
  ``TONY_GOODPUT_SPOOL``): the child atomically publishes its wire
  snapshot ~1/s; the executor's :func:`merge_wires` substitutes the
  child's breakdown for the host ledger's ``user`` span.
- The coordinator additionally attributes seconds it alone can see
  (launch provision/stage walls, elastic resync, crash-recovery walls)
  as "extras" — additive per-task seconds outside any ledger.

On top of the ledger's ``step`` intervals, :class:`StragglerDetector`
implements the classic synchronous-training failure-mode detector: a
per-task EWMA of mean step wall compared against the gang median; a
task exceeding ``factor`` x median for ``windows`` consecutive windows
is flagged (and un-flagged when it recovers).

Dependency-free (stdlib only); safe to import in the user process.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# The closed set of categories.  ``step`` is goodput; everything else is
# badput of a named flavor.  ``overhead`` is the base category: time not
# claimed by any instrumented phase (process startup, logging, ...).
CATEGORIES: Tuple[str, ...] = (
    "provision",   # waiting for the gang barrier / resources to materialize
    "stage",       # staging artifacts (venv, weights) onto the host
    "compile",     # XLA compilation walls
    "data_wait",   # input pipeline starvation (host blocked on next batch)
    "step",        # productive train-step / serve-token time (GOODPUT)
    "checkpoint",  # checkpoint save/restore walls
    "eval",        # in-loop evaluation
    "resync",      # elastic reconfiguration (shrink/regrow re-registration)
    "recovery",    # crash-recovery walls (coordinator/executor restart)
    "idle",        # intentionally idle (serve engine waiting for work)
    "queue_wait",  # waiting in the cluster daemon's queue for a grant
    "overhead",    # everything unclaimed
)

_CATEGORY_SET = frozenset(CATEGORIES)

# Category used internally by the executor's host ledger to mark "the
# user process is running"; replaced by the child's own breakdown in
# merge_wires().  Not a public category.
USER_CATEGORY = "user"

WIRE_VERSION = 1


class GoodputLedger:
    """Thread-safe interval accountant with exactly one open category.

    The ledger starts at construction time with the base category open
    (``overhead`` unless overridden).  ``enter(cat)`` pushes; on exit the
    previous category resumes.  ``snapshot()`` folds the live interval
    into the totals so the sum always equals the elapsed wall clock.
    """

    def __init__(
        self,
        base: str = "overhead",
        registry=None,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        spool_path: Optional[str] = None,
        spool_interval_s: float = 1.0,
        extra_categories: Tuple[str, ...] = (),
    ):
        allowed = _CATEGORY_SET | set(extra_categories)
        if base not in allowed:
            raise ValueError("unknown goodput category: %r" % (base,))
        self._allowed = allowed
        self._clock = clock
        self._wall_clock = wall_clock
        self._lock = threading.Lock()
        self._t0_wall = wall_clock()
        self._t0 = clock()
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        # Stack of [category, resumed_at, folded_seconds_this_frame];
        # bottom is the base category. The third field accumulates wall
        # already folded out of an interrupted frame (a nested push folds
        # the parent) so closing a "step" credits the WHOLE step wall to
        # the straggler accumulators, not just its last segment.
        self._stack: List[List] = [[base, self._t0, 0.0]]
        # step-wall accumulators for the straggler detector: closed-step
        # count and cumulative closed-step seconds (live step interval is
        # NOT included so window deltas measure completed steps only).
        self._step_closed = 0
        self._step_seconds = 0.0
        self._registry = registry
        self._shipped: Dict[str, float] = {}
        self._spool_path = spool_path
        self._spool_interval_s = spool_interval_s
        self._last_spool = 0.0

    # -- core accounting ------------------------------------------------

    def enter(self, category: str):
        """Context manager: attribute the enclosed wall time to *category*."""
        if category not in self._allowed:
            raise ValueError("unknown goodput category: %r" % (category,))
        return _Interval(self, category)

    def _push(self, category: str) -> None:
        with self._lock:
            now = self._clock()
            self._fold_top(now)
            self._stack.append([category, now, 0.0])

    def _pop(self, category: str) -> None:
        with self._lock:
            now = self._clock()
            # Tolerate out-of-order exits (e.g. a generator-held context
            # finalized late): unwind to the matching frame, folding
            # everything above it as-is. Each unwound frame's parent
            # resumes from *now* — its since still points at the child's
            # push time, and folding from there would attribute the
            # child's interval twice.
            while len(self._stack) > 1:
                top = self._fold_top(now, close=True)
                self._stack[-1][1] = now
                if top == category:
                    break
        self._maybe_spool()

    def _fold_top(self, now: float, close: bool = False):
        """Fold the top frame's elapsed time into totals (caller holds lock).

        With close=True the frame is removed and its interval count
        bumped; otherwise the frame stays open and restarts from *now*.
        """
        frame = self._stack[-1]
        cat, since = frame[0], frame[1]
        dt = max(0.0, now - since)
        if dt:
            self._totals[cat] = self._totals.get(cat, 0.0) + dt
        if close:
            self._stack.pop()
            self._counts[cat] = self._counts.get(cat, 0) + 1
            if cat == "step":
                self._step_closed += 1
                self._step_seconds += frame[2] + dt
        else:
            frame[1] = now
            frame[2] += dt
        return cat

    def add(self, category: str, seconds: float) -> None:
        """Attribute *seconds* to *category* without an interval.

        Escape hatch for walls measured elsewhere (coordinator extras use
        their own mechanism; this is for in-process pre-measured time).
        Note: added seconds are NOT part of the wall-clock invariant.
        """
        if category not in self._allowed:
            raise ValueError("unknown goodput category: %r" % (category,))
        if seconds <= 0:
            return
        with self._lock:
            self._totals[category] = self._totals.get(category, 0.0) + seconds
            self._counts[category] = self._counts.get(category, 0) + 1

    # -- snapshots ------------------------------------------------------

    def snapshot(self) -> dict:
        """Cumulative wire snapshot.  Idempotent: safe to re-send/re-ingest."""
        with self._lock:
            now = self._clock()
            self._fold_top(now)
            cats = {k: v for k, v in self._totals.items() if v > 0.0}
            wire = {
                "v": WIRE_VERSION,
                "t0": self._t0_wall,
                "now": self._t0_wall + (now - self._t0),
                "cat": cats,
                "cur": self._stack[-1][0],
                "n": dict(self._counts),
                "sw": {"c": self._step_closed, "s": self._step_seconds},
            }
        self._mirror(cats)
        return wire

    def to_wire_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def _mirror(self, cats: Dict[str, float]) -> None:
        """Delta-mirror cumulative totals into tony_goodput_seconds_total."""
        reg = self._registry
        if reg is None:
            return
        try:
            for cat, total in cats.items():
                if cat == USER_CATEGORY:
                    continue
                delta = total - self._shipped.get(cat, 0.0)
                if delta > 0:
                    reg.counter(
                        "tony_goodput_seconds_total",
                        help="wall seconds attributed by the goodput "
                             "ledger, by category",
                        category=cat,
                    ).inc(delta)
                    self._shipped[cat] = total
        except Exception:  # noqa: BLE001 - accounting must never break the task
            pass

    def _maybe_spool(self) -> None:
        path = self._spool_path
        if not path:
            return
        now = self._clock()
        if now - self._last_spool < self._spool_interval_s:
            return
        self._last_spool = now
        self.publish()

    def publish(self) -> None:
        """Atomically publish the current snapshot to the spool file."""
        path = self._spool_path
        if not path:
            return
        try:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(self.to_wire_json())
            os.replace(tmp, path)
        except OSError:
            pass


class _Interval:
    """Re-entrant-per-use context manager returned by ``ledger.enter``."""

    __slots__ = ("_ledger", "_category")

    def __init__(self, ledger: GoodputLedger, category: str):
        self._ledger = ledger
        self._category = category

    def __enter__(self):
        self._ledger._push(self._category)
        return self

    def __exit__(self, *exc):
        self._ledger._pop(self._category)
        return False


# -- wire validation / merge ------------------------------------------


def validate_wire(wire) -> Optional[dict]:
    """Return the wire dict if structurally sound, else None (drop).

    Same discipline as the metrics piggyback: a malformed payload is
    dropped (the caller logs/counts), never an error up the heartbeat.
    """
    if not isinstance(wire, dict):
        return None
    try:
        if int(wire.get("v", 0)) != WIRE_VERSION:
            return None
        t0 = float(wire["t0"])
        now = float(wire["now"])
        if now < t0:
            return None
        cat = wire.get("cat", {})
        if not isinstance(cat, dict):
            return None
        for k, v in cat.items():
            if not isinstance(k, str) or float(v) < 0:
                return None
        sw = wire.get("sw", {})
        if not isinstance(sw, dict):
            return None
        int(sw.get("c", 0))
        float(sw.get("s", 0.0))
    except (KeyError, TypeError, ValueError):
        return None
    return wire


def from_wire_json(payload: str) -> Optional[dict]:
    try:
        return validate_wire(json.loads(payload))
    except (ValueError, TypeError):
        return None


def merge_wires(host: dict, child: Optional[dict]) -> dict:
    """Merge the executor's host ledger wire with the user process's.

    The host ledger marks the user process's entire lifetime under the
    internal ``user`` category.  The child publishes its own breakdown
    of (part of) that same wall time.  The merge substitutes: host
    categories minus ``user``, plus the child's categories, plus any
    residual (user wall the child has not yet accounted for — startup,
    spool lag) credited to ``overhead``.  Step-wall accumulators come
    from the child (the host never closes steps).
    """
    merged_cat = {
        k: v for k, v in host.get("cat", {}).items() if k != USER_CATEGORY
    }
    merged_n = {
        k: v for k, v in host.get("n", {}).items() if k != USER_CATEGORY
    }
    host_user = float(host.get("cat", {}).get(USER_CATEGORY, 0.0))
    if host.get("cur") == USER_CATEGORY:
        cur = "overhead"
    else:
        cur = host.get("cur", "overhead")
    sw = {"c": 0, "s": 0.0}
    if child:
        child_sum = 0.0
        for k, v in child.get("cat", {}).items():
            v = float(v)
            child_sum += v
            merged_cat[k] = merged_cat.get(k, 0.0) + v
        for k, v in child.get("n", {}).items():
            merged_n[k] = merged_n.get(k, 0) + int(v)
        residual = host_user - child_sum
        if residual > 0:
            merged_cat["overhead"] = merged_cat.get("overhead", 0.0) + residual
        csw = child.get("sw", {})
        sw = {"c": int(csw.get("c", 0)), "s": float(csw.get("s", 0.0))}
        if host.get("cur") == USER_CATEGORY:
            cur = child.get("cur", "overhead")
    elif host_user > 0:
        # No child snapshot yet: its wall is unattributed overhead.
        merged_cat["overhead"] = merged_cat.get("overhead", 0.0) + host_user
    return {
        "v": WIRE_VERSION,
        "t0": host.get("t0", 0.0),
        "now": host.get("now", 0.0),
        "cat": merged_cat,
        "cur": cur,
        "n": merged_n,
        "sw": sw,
    }


def goodput_fraction(entry: dict) -> float:
    """Goodput fraction of a per-task goodput payload entry.

    ``entry`` is one task's dict from a GOODPUT event payload: ledger
    categories under "cat" plus coordinator-attributed seconds under
    "extra".  The denominator is the full attributed wall:
    (now - t0) + sum(extra).
    """
    cat = entry.get("cat", {})
    extra = entry.get("extra", {})
    wall = max(0.0, float(entry.get("now", 0.0)) - float(entry.get("t0", 0.0)))
    wall += sum(float(v) for v in extra.values())
    if wall <= 0:
        return 0.0
    return float(cat.get("step", 0.0)) / wall


# -- process-global ledger (user-process side) -------------------------

_default_ledger: Optional[GoodputLedger] = None
_default_lock = threading.Lock()


def get_ledger() -> GoodputLedger:
    """The process-global ledger.

    In a fork-exec'd user process, honors ``TONY_GOODPUT_SPOOL`` so the
    first caller transparently wires up the executor bridge.
    """
    global _default_ledger
    with _default_lock:
        if _default_ledger is None:
            spool = os.environ.get("TONY_GOODPUT_SPOOL") or None
            registry = None
            try:
                from tony_tpu.runtime import metrics as _metrics

                registry = _metrics.get_default()
            except Exception:  # noqa: BLE001
                pass
            _default_ledger = GoodputLedger(
                registry=registry, spool_path=spool
            )
        return _default_ledger


def set_ledger(ledger: Optional[GoodputLedger]) -> None:
    global _default_ledger
    with _default_lock:
        _default_ledger = ledger


# -- straggler detection ----------------------------------------------


class StragglerDetector:
    """Flag tasks whose step wall persistently exceeds the gang median.

    Fed one merged goodput wire per task per window (the coordinator's
    monitor loop calls :meth:`observe` on the ``tony.goodput.window-ms``
    cadence).  Per task, the mean step wall over the window is the delta
    of the wire's cumulative step accumulators; an EWMA smooths it.  A
    task is *suspected* when its EWMA exceeds ``factor`` x the gang
    median EWMA for ``windows`` consecutive windows, and *cleared* the
    first window it drops back under.  Windows that closed no steps are
    skipped (checkpoint pauses are not evidence).

    Pure logic, no I/O: returns (suspected, cleared) transition lists;
    the coordinator turns those into jhist events / counters / flight
    entries.
    """

    def __init__(self, factor: float = 2.0, windows: int = 3, alpha: float = 0.3):
        self.factor = max(1.0, float(factor))
        self.windows = max(1, int(windows))
        self.alpha = alpha
        # task_id -> (last step count, last step seconds, ewma, strikes)
        self._state: Dict[str, List[float]] = {}
        self._suspected: Dict[str, dict] = {}

    @staticmethod
    def gang_of(task_id: str) -> str:
        return task_id.split(":", 1)[0]

    def forget(self, task_id: str) -> None:
        self._state.pop(task_id, None)
        self._suspected.pop(task_id, None)

    @property
    def suspected(self) -> Dict[str, dict]:
        """Currently-suspected tasks -> evidence dict."""
        return dict(self._suspected)

    def observe(self, wires: Dict[str, dict]) -> Tuple[List[dict], List[str]]:
        """Ingest one window of per-task wires; return transitions.

        Returns (newly_suspected, newly_cleared): the former as evidence
        dicts ({task, gang, ewma_s, median_s, factor, windows}), the
        latter as task ids.
        """
        # 1. Update EWMAs from step-accumulator deltas.
        ewmas: Dict[str, float] = {}
        for task_id, wire in wires.items():
            sw = wire.get("sw") or {}
            c = int(sw.get("c", 0))
            s = float(sw.get("s", 0.0))
            st = self._state.get(task_id)
            if st is None:
                self._state[task_id] = [c, s, 0.0, 0]
                continue
            dc, ds = c - st[0], s - st[1]
            st[0], st[1] = c, s
            if dc <= 0 or ds < 0:
                continue  # no steps closed this window: not evidence
            mean = ds / dc
            st[2] = mean if st[2] == 0.0 else (
                self.alpha * mean + (1 - self.alpha) * st[2]
            )
        for task_id, st in self._state.items():
            if st[2] > 0.0:
                ewmas[task_id] = st[2]

        # 2. Compare against the gang median.
        gangs: Dict[str, List[float]] = {}
        for task_id, ewma in ewmas.items():
            gangs.setdefault(self.gang_of(task_id), []).append(ewma)

        suspected: List[dict] = []
        cleared: List[str] = []
        for task_id, ewma in ewmas.items():
            gang = self.gang_of(task_id)
            vals = sorted(gangs[gang])
            if len(vals) < 2:
                continue  # a gang of one has no peers to lag behind
            median = vals[len(vals) // 2] if len(vals) % 2 else (
                (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2.0
            )
            st = self._state[task_id]
            slow = median > 0 and ewma > self.factor * median
            if slow:
                st[3] = int(st[3]) + 1
                if st[3] >= self.windows and task_id not in self._suspected:
                    evidence = {
                        "task": task_id,
                        "gang": gang,
                        "ewma_s": round(ewma, 6),
                        "median_s": round(median, 6),
                        "factor": self.factor,
                        "windows": self.windows,
                    }
                    self._suspected[task_id] = evidence
                    suspected.append(evidence)
            else:
                st[3] = 0
                if task_id in self._suspected:
                    del self._suspected[task_id]
                    cleared.append(task_id)
        return suspected, cleared
