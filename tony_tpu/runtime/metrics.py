"""Cluster-wide metrics plane: the in-process registry and its codecs.

The reference ships per-task resource metrics from every executor to the AM
over a dedicated RPC (reference: TaskMonitor.java + MetricsRpc, surfaced in
the history server). This module is the TPU build's substrate for the same
capability, shared by every layer:

- producers (``models/train.py``, ``models/serve.py``,
  ``cluster/executor.py``, ``cluster/liveness.py``) observe into the
  process-wide default :class:`MetricsRegistry`;
- the executor's heartbeater serializes the registry with :func:`to_wire`
  and piggybacks it on each heartbeat (``rpc/client.py`` →
  ``rpc/server.py``);
- the coordinator keeps the last snapshot per task in a
  :class:`SnapshotTable` and folds the table into periodic
  ``METRICS_SNAPSHOT`` events in the jhist stream (``events/events.py``);
- the history server replays those events into Prometheus text exposition
  (:func:`render_prometheus`) and JSON (``history/server.py``).

Design constraints (this sits on the serve hot loop):

- **dependency-free** — stdlib only, importable from the executor, the
  coordinator, and user training processes alike;
- **O(1) per observation, no locks on read-mostly paths** — instrument
  lookup is a plain dict read; ``inc``/``observe`` take a per-instrument
  lock (a read-modify-write like ``+=`` is NOT GIL-atomic, so lock-free
  writers would silently lose concurrent increments; an uncontended
  acquire is ~100 ns, pinned under 1 % of serve chunk wall by bench.py's
  metrics-overhead arm). Snapshot/render READS stay lock-free — a reader
  may see a histogram's ``sum`` and ``count`` momentarily torn, which
  monitoring tolerates by design (telemetry, not accounting). The
  registry lock is taken only when an instrument is first created.
"""

from __future__ import annotations

import bisect
import json
import logging
import math
import os
import re
import threading
import time

log = logging.getLogger(__name__)

#: default histogram bucket bounds for wall-clock seconds (le-style,
#: +Inf implicit) — spans µs-scale registry costs to minute-scale steps
TIME_BUCKETS_S: tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

def parse_latency_buckets(spec: str) -> tuple[float, ...]:
    """Parse a ``tony.metrics.latency-buckets`` value — comma-separated
    upper bounds in seconds — into a histogram bucket ladder. Empty/
    blank means the built-in :data:`TIME_BUCKETS_S` (the pre-QoS
    bounds, so unconfigured deployments render identical series).
    Raises ``ValueError`` on anything malformed: non-numeric or
    non-finite bounds, non-positive bounds, or a non-strictly-increasing
    ladder — refused at CONFIG LOAD, because a bad ladder discovered at
    the first ``observe`` would take the serve loop down instead of the
    operator's deploy."""
    if not isinstance(spec, str):
        raise ValueError(f"latency buckets must be a string, got "
                         f"{type(spec).__name__}")
    if not spec.strip():
        return TIME_BUCKETS_S
    bounds = []
    for part in spec.split(","):
        try:
            b = float(part.strip())
        except ValueError:
            raise ValueError(
                f"bad latency bucket bound {part.strip()!r} "
                f"(want a number of seconds)") from None
        if not math.isfinite(b) or b <= 0.0:
            raise ValueError(
                f"latency bucket bounds must be finite and positive, "
                f"got {part.strip()!r}")
        bounds.append(b)
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo:
            raise ValueError(
                f"latency bucket bounds must be strictly increasing, "
                f"got {lo} before {hi}")
    return tuple(bounds)


_KIND_COUNTER = "counter"
_KIND_GAUGE = "gauge"
_KIND_HISTOGRAM = "histogram"


def _labels_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing value. ``inc`` locks per instrument —
    ``+=`` is a preemptible read-modify-write, and a lost increment is a
    permanent undercount on a counter; ``value`` reads lock-free."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-set value (may go up or down). ``set`` is a single atomic
    store (no lock needed); ``inc`` read-modify-writes under a lock."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative rendering happens at export).

    ``observe`` is one ``bisect`` + three increments under the
    per-instrument lock — O(log #buckets) with a handful of buckets,
    effectively O(1). Reads don't lock (sum/count may be torn)."""

    __slots__ = ("name", "labels", "buckets", "_lock", "_counts", "_sum",
                 "_count")

    def __init__(self, name: str, labels: dict[str, str],
                 buckets: tuple[float, ...] = TIME_BUCKETS_S) -> None:
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        # one slot per finite bound plus the +Inf overflow slot
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> list[int]:
        """Per-bound cumulative counts (Prometheus ``le`` semantics),
        +Inf last."""
        out, running = [], 0
        for c in self._counts:
            running += c
            out.append(running)
        return out


def histogram_quantile(snapshot, q: float) -> float:
    """Bucket-interpolated quantile, Prometheus ``histogram_quantile``
    semantics.

    ``snapshot`` is either a live :class:`Histogram` or its wire dict
    (``{"b": bounds, "n": per-bucket counts (+Inf last), ...}``).  The
    target rank ``q * count`` is located in the cumulative bucket
    counts, then linearly interpolated between the bucket's bounds (the
    first bucket's lower bound is 0).  A rank landing in the +Inf bucket
    returns the highest finite bound (the classic prometheus caveat: an
    unbounded bucket has no interior to interpolate).  Empty histogram
    -> NaN.
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError("quantile must be in [0, 1]")
    if isinstance(snapshot, dict):
        bounds = [float(b) for b in snapshot.get("b", [])]
        counts = [int(c) for c in snapshot.get("n", [])]
    else:
        bounds = list(snapshot.buckets)
        counts = list(snapshot._counts)
    if not bounds or len(counts) != len(bounds) + 1:
        return float("nan")
    total = sum(counts)
    if total == 0:
        return float("nan")
    rank = q * total
    running = 0.0
    for i, c in enumerate(counts):
        running += c
        if running >= rank and c > 0:
            if i >= len(bounds):          # +Inf bucket
                return bounds[-1]
            lower = bounds[i - 1] if i > 0 else 0.0
            upper = bounds[i]
            frac = (rank - (running - c)) / c
            return lower + (upper - lower) * frac
    return bounds[-1]


class MetricsRegistry:
    """Thread-safe instrument registry with get-or-create semantics.

    One metric NAME has one kind (and one help string and, for
    histograms, one bucket ladder); label sets distinguish series under
    it. Lookup of an existing instrument is a single dict read.
    """

    def __init__(self) -> None:
        self._instruments: dict[tuple, object] = {}
        self._meta: dict[str, tuple[str, str]] = {}   # name -> (kind, help)
        self._lock = threading.Lock()

    # -- get-or-create ------------------------------------------------------
    def _get(self, kind: str, name: str, help: str, labels: dict,
             factory, cls: type):
        key = (name, _labels_key(labels))
        inst = self._instruments.get(key)      # lock-free fast path
        if inst is not None:
            if type(inst) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__.lower()}, cannot use as {kind}")
            return inst
        with self._lock:
            inst = self._instruments.get(key)
            if inst is not None:
                return inst
            meta = self._meta.get(name)
            if meta is not None and meta[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {meta[0]}, "
                    f"cannot re-register as {kind}")
            if meta is None or (help and not meta[1]):
                self._meta[name] = (kind, help)
            inst = factory()
            self._instruments[key] = inst
            return inst

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(_KIND_COUNTER, name, help, labels,
                         lambda: Counter(name, dict(labels)), Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(_KIND_GAUGE, name, help, labels,
                         lambda: Gauge(name, dict(labels)), Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = TIME_BUCKETS_S,
                  **labels) -> Histogram:
        return self._get(_KIND_HISTOGRAM, name, help, labels,
                         lambda: Histogram(name, dict(labels), buckets),
                         Histogram)

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()
            self._meta.clear()

    # -- snapshots ----------------------------------------------------------
    def to_wire(self) -> dict:
        """Compact, JSON-safe snapshot of every series (the heartbeat
        payload). Keys: ``c``/``g``/``h`` hold ``[name, {labels},
        value]`` triples (histogram value = ``{"b": bounds, "n":
        per-bucket counts, "s": sum, "c": count}``); ``m`` maps metric
        name to ``[kind, help]``."""
        c, g, h = [], [], []
        for (name, _), inst in list(self._instruments.items()):
            if isinstance(inst, Counter):
                c.append([name, inst.labels, inst.value])
            elif isinstance(inst, Gauge):
                g.append([name, inst.labels, inst.value])
            elif isinstance(inst, Histogram):
                h.append([name, inst.labels,
                          {"b": list(inst.buckets), "n": list(inst._counts),
                           "s": inst.sum, "c": inst.count}])
        return {"c": c, "g": g, "h": h,
                "m": {n: list(km) for n, km in self._meta.items()}}

    def to_wire_json(self) -> str:
        return json.dumps(self.to_wire(), separators=(",", ":"))


class NullRegistry(MetricsRegistry):
    """A registry whose instruments swallow every observation — the
    zero-cost-contrast arm for overhead benchmarks (``bench.py``)."""

    class _Null:
        name = "null"
        labels: dict = {}
        value = 0.0
        count = 0
        sum = 0.0
        buckets: tuple = (1.0,)

        def inc(self, amount: float = 1.0) -> None: ...
        def set(self, value: float) -> None: ...
        def observe(self, value: float) -> None: ...
        def cumulative(self) -> list: return [0, 0]

    _NULL = _Null()

    def counter(self, name, help="", **labels): return self._NULL
    def gauge(self, name, help="", **labels): return self._NULL
    def histogram(self, name, help="", buckets=TIME_BUCKETS_S, **labels):
        return self._NULL
    def to_wire(self) -> dict:
        return {"c": [], "g": [], "h": [], "m": {}}


_default = MetricsRegistry()
_default_lock = threading.Lock()


def get_default() -> MetricsRegistry:
    """The process-wide registry every producer observes into."""
    return _default


def set_default(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the default registry (tests, bench contrast arms). Returns
    the previous one."""
    global _default
    with _default_lock:
        prev = _default
        _default = registry
    return prev


# ---------------------------------------------------------------------------
# Wire validation / decoding (coordinator + history-server side)
# ---------------------------------------------------------------------------
#: Prometheus-legal metric names / label keys. Enforced at ingest so one
#: task's bad name can never corrupt the exposition for the whole fleet
#: (a space or newline in a series name is a scrape-wide parse error).
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_KEY_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _check_number(v, what: str) -> None:
    # bool is an int subclass; NaN/Infinity parse as valid JSON numbers
    # under json.loads' defaults — both would poison the exposition
    if isinstance(v, bool) or not isinstance(v, (int, float)) \
            or not math.isfinite(v):
        raise ValueError(f"non-finite or non-numeric {what}: {v!r}")


def validate_wire(wire: dict) -> dict:
    """Structurally validate a snapshot produced by :meth:`to_wire` —
    shape, element types, finiteness, and Prometheus-legal names/label
    keys, so anything that passes here renders cleanly. Raises
    ``ValueError`` on anything malformed; returns the dict."""
    if not isinstance(wire, dict):
        raise ValueError("snapshot is not an object")
    for kind in ("c", "g", "h"):
        entries = wire.get(kind, [])
        if not isinstance(entries, list):
            raise ValueError(f"snapshot[{kind!r}] is not a list")
        for e in entries:
            if (not isinstance(e, (list, tuple)) or len(e) != 3
                    or not isinstance(e[0], str)
                    or not isinstance(e[1], dict)):
                raise ValueError(f"malformed series entry: {e!r}")
            if not _METRIC_NAME_RE.match(e[0]):
                raise ValueError(f"illegal metric name: {e[0]!r}")
            for k, v in e[1].items():
                if not isinstance(k, str) or not _LABEL_KEY_RE.match(k):
                    raise ValueError(f"illegal label key: {k!r}")
                if not isinstance(v, (str, int, float, bool)):
                    raise ValueError(f"illegal label value: {v!r}")
            if kind == "h":
                v = e[2]
                if (not isinstance(v, dict)
                        or not isinstance(v.get("b"), list)
                        or not isinstance(v.get("n"), list)
                        or len(v["n"]) != len(v["b"]) + 1
                        or not all(isinstance(n, int)
                                   and not isinstance(n, bool) and n >= 0
                                   for n in v["n"])
                        or not isinstance(v.get("c"), int)
                        or isinstance(v.get("c"), bool)
                        or v["c"] < 0):
                    # element types matter: a non-numeric bound or count
                    # that slipped through here would crash the Prometheus
                    # renderer and 500 the whole /metrics scrape
                    raise ValueError(f"malformed histogram value: {v!r}")
                for b in v["b"]:
                    _check_number(b, "histogram bound")
                if v["b"] != sorted(v["b"]):
                    # Prometheus requires le-ordered buckets
                    raise ValueError(f"unsorted histogram bounds: {v['b']!r}")
                # .get: a MISSING "s" must be a ValueError here, not a
                # KeyError that escapes ingest's catch and fails the beat
                _check_number(v.get("s"), "histogram sum")
            else:
                _check_number(e[2], "series value")
    meta = wire.get("m", {})
    if not isinstance(meta, dict):
        raise ValueError("snapshot['m'] is not an object")
    for name, km in meta.items():
        # series_from_wire indexes km[1] — a non-sequence or non-string
        # meta value passing here would TypeError at render time and
        # 500 the whole scrape
        if (not isinstance(name, str)
                or not isinstance(km, (list, tuple)) or not km
                or not all(isinstance(x, str) for x in km)):
            raise ValueError(f"malformed meta entry: {name!r}: {km!r}")
    return wire


def from_wire_json(payload: str) -> dict:
    """Parse + validate a JSON heartbeat snapshot. Raises ValueError."""
    try:
        wire = json.loads(payload)
    except json.JSONDecodeError as e:
        raise ValueError(f"snapshot is not JSON: {e}") from e
    return validate_wire(wire)


class SnapshotTable:
    """Coordinator-side table of each task's LAST metrics snapshot.

    ``ingest`` never raises — a malformed snapshot from one executor must
    not kill the coordinator's heartbeat handler (it is logged and
    dropped; the previous good snapshot, if any, is kept)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_task: dict[str, dict] = {}
        self._rejects = 0

    def ingest(self, task_id: str, payload: str | dict) -> bool:
        try:
            wire = (validate_wire(payload) if isinstance(payload, dict)
                    else from_wire_json(payload))
        except (ValueError, TypeError):
            with self._lock:        # gRPC handler threads race here
                self._rejects += 1
            log.warning("dropping malformed metrics snapshot from %s",
                        task_id, exc_info=True)
            return False
        with self._lock:
            self._by_task[task_id] = wire
        return True

    def clear(self) -> None:
        with self._lock:
            self._by_task.clear()

    @property
    def rejected(self) -> int:
        return self._rejects

    def tasks(self) -> list[str]:
        with self._lock:
            return sorted(self._by_task)

    def get(self, task_id: str) -> dict | None:
        with self._lock:
            return self._by_task.get(task_id)

    def as_payload(self) -> dict[str, dict]:
        """{task_id: wire snapshot} — the METRICS_SNAPSHOT event body."""
        with self._lock:
            return dict(self._by_task)


# ---------------------------------------------------------------------------
# Bridges from existing instrumentation
# ---------------------------------------------------------------------------
def observe_phase_times(phase_times, registry: MetricsRegistry | None = None,
                        prefix: str = "tony_serve_phase") -> None:
    """Fold a :class:`tony_tpu.runtime.profiler.PhaseTimes` summary into
    the registry: per phase, ``<prefix>_seconds_total`` (host wall spent)
    and ``<prefix>_ops_total`` (times entered) counters, labeled
    ``phase=<name>``. Called once per ``serve()`` — each call ADDS that
    call's accumulation, so the counters stay monotonic across calls."""
    reg = registry or get_default()
    for phase, row in phase_times.summary().items():
        reg.counter(f"{prefix}_seconds_total",
                    help="host wall seconds per serve-loop phase",
                    phase=phase).inc(row["total_s"])
        reg.counter(f"{prefix}_ops_total",
                    help="serve-loop phase entries", phase=phase).inc(
                        row["count"])


_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PROCESS_START = time.monotonic()


def sample_host_stats(registry: MetricsRegistry | None = None) -> None:
    """Sample this process's /proc stats into gauges: RSS bytes, CPU
    seconds (user+sys, cumulative), and process uptime. No-op (uptime
    only) where /proc is unavailable."""
    reg = registry or get_default()
    reg.gauge("tony_process_uptime_seconds",
              help="seconds since this process imported the metrics "
                   "module").set(time.monotonic() - _PROCESS_START)
    try:
        with open("/proc/self/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # fields after the parenthesized comm (which may contain spaces)
        rest = stat.rsplit(")", 1)[1].split()
        utime, stime = int(rest[11]), int(rest[12])   # fields 14/15
        rss_pages = int(rest[21])                      # field 24
        reg.gauge("tony_process_cpu_seconds",
                  help="cumulative user+system CPU seconds").set(
                      (utime + stime) / float(_CLK_TCK))
        reg.gauge("tony_process_rss_bytes",
                  help="resident set size in bytes").set(
                      rss_pages * _PAGE_SIZE)
    except (OSError, IndexError, ValueError):
        pass                         # non-Linux / constrained container
    try:
        # Open-fd count next to RSS/CPU: the cheap early-warning for the
        # launch-path fd-leak class (a leaked pipe per task launch grows
        # this linearly with restarts).
        reg.gauge("tony_task_open_fds",
                  help="open file descriptors in this process").set(
                      len(os.listdir("/proc/self/fd")))
    except OSError:
        pass                         # non-Linux / constrained container


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------
def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def series_from_wire(wire: dict, extra_labels: dict[str, str] | None = None,
                     ) -> list[tuple]:
    """Flatten a wire snapshot into ``(kind, name, labels, value, help)``
    entries, merging ``extra_labels`` (e.g. ``{"job": app_id, "task":
    task_id}``) into each series — the exporter-side join that keeps
    per-task series distinct in a fleet-wide scrape."""
    extra = dict(extra_labels or {})
    meta = wire.get("m", {})
    out = []
    for kind_key, kind in (("c", _KIND_COUNTER), ("g", _KIND_GAUGE),
                           ("h", _KIND_HISTOGRAM)):
        for name, labels, value in wire.get(kind_key, []):
            m = meta.get(name, [kind, ""])
            out.append((kind, name, {**labels, **extra}, value,
                        m[1] if len(m) > 1 else ""))
    return out


def render_prometheus(entries: list[tuple]) -> str:
    """Render ``(kind, name, labels, value, help)`` entries as Prometheus
    text exposition (format 0.0.4): one ``# HELP``/``# TYPE`` pair per
    metric name, histogram expansion to ``_bucket``/``_sum``/``_count``,
    duplicate series dropped (last write wins)."""
    by_name: dict[str, list] = {}
    kinds: dict[str, str] = {}
    helps: dict[str, str] = {}
    for kind, name, labels, value, help_ in entries:
        if kinds.setdefault(name, kind) != kind:
            log.warning("metric %s seen as both %s and %s — keeping %s",
                        name, kinds[name], kind, kinds[name])
            continue
        if help_ and not helps.get(name):
            helps[name] = help_
        # duplicate-series guard: same (name, labels) keeps the LAST value
        bucket = by_name.setdefault(name, [])
        key = _labels_key(labels)
        bucket[:] = [(k, l, v) for (k, l, v) in bucket
                     if _labels_key(l) != key]
        bucket.append((kind, labels, value))
    lines = []
    for name in sorted(by_name):
        kind = kinds[name]
        help_txt = (helps.get(name) or name).replace(
            "\\", "\\\\").replace("\n", "\\n")
        lines.append(f"# HELP {name} {help_txt}")
        lines.append(f"# TYPE {name} {kind}")
        for _, labels, value in by_name[name]:
            if kind == _KIND_HISTOGRAM:
                bounds = value["b"]
                running = 0
                for bound, n in zip(bounds + [float("inf")], value["n"]):
                    running += n
                    le = "+Inf" if bound == float("inf") else _fmt_value(bound)
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels({**labels, 'le': le})}"
                        f" {running}")
                lines.append(f"{name}_sum{_fmt_labels(labels)}"
                             f" {_fmt_value(value['s'])}")
                lines.append(f"{name}_count{_fmt_labels(labels)}"
                             f" {value['c']}")
            else:
                lines.append(f"{name}{_fmt_labels(labels)}"
                             f" {_fmt_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_registry(registry: MetricsRegistry | None = None,
                    extra_labels: dict[str, str] | None = None) -> str:
    """Prometheus text for a live in-process registry."""
    reg = registry or get_default()
    return render_prometheus(series_from_wire(reg.to_wire(), extra_labels))
