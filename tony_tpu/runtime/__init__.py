"""Task-side runtime: consume the coordinator-exported environment.

The user-script-facing half of the runtime adapter. The reference exports
TF_CONFIG and the user script feeds it to ``tf.train.Server`` (reference:
tony-examples/mnist-tensorflow/mnist_distributed.py:190-227); here the
executor exports the ``TONY_JAX_*`` bootstrap (tony_tpu/cluster/executor.py)
and the user script calls :func:`initialize` + :func:`mesh`:

    import tony_tpu.runtime as rt
    rt.initialize()                 # jax.distributed bootstrap (no-op 1-proc)
    mesh = rt.mesh()                # Mesh over ALL devices, axes from config
    ...pjit/shard_map under `mesh`...

Works identically on a real TPU slice, on multi-process CPU (the fake-cluster
E2E path), and single-process (mesh over local devices).
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass

from tony_tpu import constants

log = logging.getLogger(__name__)

_initialized = False


@dataclass(frozen=True)
class TaskInfo:
    job_name: str
    task_index: int
    task_num: int
    session_id: int
    attempt: int
    process_id: int
    num_processes: int
    coordinator_address: str
    cluster_spec: dict

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def task_info() -> TaskInfo:
    """Parse the executor-exported environment (works outside tony too,
    defaulting to a single local process)."""
    spec = os.environ.get(constants.CLUSTER_SPEC, "")
    return TaskInfo(
        job_name=os.environ.get(constants.JOB_NAME, "worker"),
        task_index=int(os.environ.get(constants.TASK_INDEX, "0")),
        task_num=int(os.environ.get(constants.TASK_NUM, "1")),
        session_id=int(os.environ.get(constants.SESSION_ID, "0")),
        attempt=int(os.environ.get(constants.ATTEMPT_NUMBER, "0")),
        process_id=int(os.environ.get(constants.JAX_PROCESS_ID, "0")),
        num_processes=int(os.environ.get(constants.JAX_NUM_PROCESSES, "1")),
        coordinator_address=os.environ.get(constants.JAX_COORDINATOR_ADDRESS, ""),
        cluster_spec=json.loads(spec) if spec else {},
    )


def initialize() -> TaskInfo:
    """Bootstrap ``jax.distributed`` from the coordinator-assigned identity —
    the direct analog of the reference's TF_CONFIG consumption. Idempotent;
    no-op for single-process jobs and bare (non-tony) runs."""
    global _initialized
    info = task_info()
    if _initialized:
        return info
    if info.is_distributed and info.coordinator_address:
        import jax
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # Multi-process CPU (the fake-cluster test path) needs an
            # explicit cross-process collectives implementation.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        log.info("jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
                 info.coordinator_address, info.num_processes, info.process_id)
        jax.distributed.initialize(
            coordinator_address=info.coordinator_address,
            num_processes=info.num_processes,
            process_id=info.process_id)
    from tony_tpu.runtime import profiler
    profiler.maybe_start()
    _initialized = True
    return info


def mesh_axes() -> dict[str, int]:
    """The mesh layout shipped by the coordinator (tony.application.mesh),
    or {} when unset."""
    raw = os.environ.get(constants.MESH_SPEC, "")
    if not raw:
        return {}
    return json.loads(raw).get("axes", {})


def mesh_dcn_axes() -> dict[str, int]:
    """Cross-slice (DCN) mesh layout (tony.application.mesh.dcn), or {}
    for single-slice jobs."""
    raw = os.environ.get(constants.MESH_SPEC, "")
    if not raw:
        return {}
    return json.loads(raw).get("dcn_axes", {})


def slice_info() -> tuple[int, int]:
    """(slice_id, num_slices) of this host's gang — (0, 1) when the job
    type is single-slice (tony.{job}.slices unset or 1)."""
    return (int(os.environ.get(constants.SLICE_ID, "0")),
            int(os.environ.get(constants.NUM_SLICES, "1")))


def mesh(axes: dict[str, int] | None = None,
         axis_order: tuple[str, ...] | None = None,
         dcn_axes: dict[str, int] | None = None):
    """Build a ``jax.sharding.Mesh`` over ALL devices (all processes).

    ``axes`` defaults to the config-shipped layout; a single axis given as
    -1/0 is inferred from the global device count (so the layout scales with
    the slice). Returns a 1-axis ``("dp",)`` mesh when nothing is configured.
    When the job is multi-slice and DCN axes are configured
    (tony.application.mesh.dcn), the mesh is hybrid: dcn axes span slices,
    ici axes stay within a slice. Delegates to
    :mod:`tony_tpu.parallel.mesh` — one implementation of axis
    inference/ordering for the whole framework.
    """
    from tony_tpu.parallel.mesh import make_hybrid_mesh, make_mesh
    axes = axes if axes is not None else mesh_axes()
    dcn = dcn_axes if dcn_axes is not None else mesh_dcn_axes()
    if dcn:
        if axis_order is not None:
            # silently dropping the caller's order would remap their
            # PartitionSpecs onto the wrong axes
            raise ValueError("axis_order is not supported for hybrid "
                             "(multi-slice) meshes: the order is fixed to "
                             "dcn-major/ici-minor")
        return make_hybrid_mesh(axes, dcn)
    return make_mesh(axes, axis_order=axis_order)
