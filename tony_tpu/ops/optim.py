"""Fused AdamW: the whole optimizer update in one HBM pass per leaf.

The optax chain (clip_by_global_norm → scale_by_adam → add_decayed_weights →
scale_by_learning_rate → apply_updates) lowers to several elementwise HLOs
whose fusion boundaries XLA does not always collapse — measured ~2 ms/step
at 60 M params on one v5e (docs/performance.md "Known headroom"). This
kernel reads (param, grad, m, v) once, does all the moment/bias-correction/
decay math in VMEM at f32, and writes (param, m, v) once — the HBM-bandwidth
floor for the update. Aliasing (param, m, v) in→out keeps it allocation-free
under donation.

Numerics: moments are stored f32 (optax inherits the grads' dtype, so bf16
params would otherwise get bf16 moments — a precision regression this path
fixes for free); params round to their storage dtype once per step, exactly
like optax.apply_updates. The global-norm clip stays an XLA reduction over
the grads (a cross-leaf global value cannot fuse into a per-leaf kernel) —
its result enters the kernel as a scalar scale.

The reference delegates optimization entirely to user TF/PyTorch code; this
is part of the compute layer the TPU build owns (SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BLOCK_ROWS = 2048          # (2048, 128) f32×5 + bf16×2 ≈ 5.5 MB of VMEM


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _adamw_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref,
                  po_ref, mo_ref, vo_ref):
    """One block of the fused update. sc: SMEM scalars
    [lr, b1, b2, eps, wd, 1/bias_corr1, 1/bias_corr2, clip_scale]."""
    lr, b1, b2, eps = sc_ref[0], sc_ref[1], sc_ref[2], sc_ref[3]
    wd, inv_bc1, inv_bc2, clip = sc_ref[4], sc_ref[5], sc_ref[6], sc_ref[7]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * clip
    m = b1 * m_ref[...].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[...].astype(jnp.float32) + (1.0 - b2) * g * g
    m_hat = m * inv_bc1
    v_hat = v * inv_bc2
    update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    po_ref[...] = (p - lr * update).astype(po_ref.dtype)
    mo_ref[...] = m.astype(mo_ref.dtype)
    vo_ref[...] = v.astype(vo_ref.dtype)


def _leaf_view(shape: tuple[int, ...]) -> tuple[int, ...] | None:
    """A relayout-free 2-D/3-D view for the kernel, or None for the XLA
    fallback. TPU arrays are tiled on their last two dims, so any reshape
    that regroups them forces a physical copy — which costs more than the
    kernel saves. The view therefore always PRESERVES the trailing dims:
    [..., minor%128==0] collapses to (rows, minor); [..., sub, minor<128]
    (the d_model→heads×head_dim projection leaves) keeps (rows, sub,
    minor) so the kernel reads the array's native half-lane tiles."""
    if len(shape) >= 2 and shape[-1] % _LANES == 0:
        return (-1, shape[-1])
    if (len(shape) >= 3 and shape[-1] < _LANES
            and shape[-1] % 8 == 0 and shape[-2] % 8 == 0):
        return (-1, shape[-2], shape[-1])
    return None


def _view_rows(shape: tuple[int, ...]):
    """(view, tail, rows) for a leaf — the single source of the blocking
    geometry, shared by the kernel gate and the kernel call."""
    view = _leaf_view(shape)
    if view is None:
        return None, (), 0
    tail = shape[len(shape) - len(view) + 1:]
    rows = _prod(shape) // _prod(tail)
    return view, tail, rows


_VMEM_BUDGET = 4 << 20       # per-operand-set block bytes (7 arrays ≈ 18B/el)


def _fused_leaf_update(p: jax.Array, g: jax.Array, m: jax.Array,
                       v: jax.Array, scalars: jax.Array):
    """Apply the kernel to one leaf via its relayout-free view."""
    view, tail, rows = _view_rows(p.shape)   # rows%8==0: caller-gated
    per_row = _prod(tail)
    # VMEM sizing uses the PADDED row: a sub-128 minor dim occupies full
    # 128-lane tiles in VMEM, so (8, 64) tails cost 2× their logical bytes
    padded_row = (per_row // tail[-1]) * (-(-tail[-1] // _LANES) * _LANES)
    br = max(8, min(_BLOCK_ROWS, _VMEM_BUDGET // (padded_row * 18)))
    br = min(br - br % 8, rows)
    p2, g2, m2, v2 = (x.reshape(view) for x in (p, g, m, v))
    nd = len(tail) + 1
    block = (br,) + tail
    idx = (lambda i: (i, 0)) if nd == 2 else (lambda i: (i, 0, 0))
    spec = pl.BlockSpec(block, idx)
    out = pl.pallas_call(
        _adamw_kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec, spec, spec, spec],
        out_specs=[pl.BlockSpec(block, idx, memory_space=pltpu.VMEM)] * 3,
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v.dtype)],
        # alias p/m/v through: the update is in-place under donation
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=_interpret(),
    )(scalars, p2, g2, m2, v2)
    new_p, new_m, new_v = out
    return (new_p.reshape(p.shape), new_m.reshape(p.shape),
            new_v.reshape(p.shape))


def _xla_leaf_update(p, g, m, v, scalars):
    """Plain-XLA fallback for leaves whose size doesn't tile 128 lanes
    (rare: a stray odd-width norm). Same math, same dtypes."""
    lr, b1, b2, eps, wd, inv_bc1, inv_bc2, clip = [scalars[i]
                                                   for i in range(8)]
    g = g.astype(jnp.float32) * clip
    new_m = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
    new_v = b2 * v.astype(jnp.float32) + (1.0 - b2) * g * g
    update = (new_m * inv_bc1) / (jnp.sqrt(new_v * inv_bc2) + eps) \
        + wd * p.astype(jnp.float32)
    return ((p.astype(jnp.float32) - lr * update).astype(p.dtype),
            new_m.astype(m.dtype), new_v.astype(v.dtype))


class FusedAdamWState(NamedTuple):
    count: jax.Array          # int32 step counter
    mu: Any                   # f32 first-moment pytree
    nu: Any                   # f32 second-moment pytree


class FusedAdamW:
    """Fused clip-by-global-norm + AdamW + schedule.

    Matches ``optax.chain(optax.clip_by_global_norm(clip_norm),
    optax.adamw(lr, b1, b2, eps, weight_decay, mu_dtype=f32))`` to fp
    tolerance (tests/test_ops.py parity test), executed as one kernel pass
    per leaf. Consumed by ``make_train_step`` through the ``fused_apply``
    protocol: ``(grads, state, params) -> (new_params, new_state, gnorm)``
    — the params update happens inside, so no separate apply_updates pass.
    """

    def __init__(self, learning_rate: float | Callable[[jax.Array], Any],
                 b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 1e-4, clip_norm: float | None = 1.0,
                 mu_dtype: Any = jnp.float32):
        self._lr = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay
        self.clip_norm = clip_norm
        #: moment storage dtype. f32 (default) is the safe choice; bf16
        #: halves the optimizer-state HBM traffic (~0.5 GB/step at 66 M
        #: params) and matches what optax gives bf16 models implicitly.
        self.mu_dtype = mu_dtype

    def init(self, params: Any) -> FusedAdamWState:
        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, self.mu_dtype), params)
        return FusedAdamWState(count=jnp.zeros((), jnp.int32),
                               mu=zeros,
                               nu=jax.tree.map(jnp.copy, zeros))

    def fused_apply(self, grads: Any, state: FusedAdamWState, params: Any):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        # schedules see the PRE-increment count, matching optax's
        # scale_by_schedule (first step evaluates the schedule at 0)
        lr = (self._lr(state.count) if callable(self._lr) else self._lr)
        gnorm = _global_norm(grads)
        if self.clip_norm is not None:
            clip = jnp.where(gnorm < self.clip_norm, 1.0,
                             self.clip_norm / jnp.maximum(gnorm, 1e-20))
        else:
            clip = jnp.ones((), jnp.float32)
        scalars = jnp.stack([
            jnp.asarray(lr, jnp.float32),
            jnp.float32(self.b1), jnp.float32(self.b2),
            jnp.float32(self.eps), jnp.float32(self.weight_decay),
            1.0 / (1.0 - jnp.float32(self.b1) ** cf),
            1.0 / (1.0 - jnp.float32(self.b2) ** cf),
            clip.astype(jnp.float32),
        ])

        leaves_p, tdef = jax.tree.flatten(params)
        leaves_g = tdef.flatten_up_to(grads)
        leaves_m = tdef.flatten_up_to(state.mu)
        leaves_v = tdef.flatten_up_to(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(leaves_p, leaves_g, leaves_m, leaves_v):
            view, _, rows = _view_rows(p.shape)
            # small leaves (norms) go to XLA — it fuses them with the
            # global-norm reduction for free, and a kernel dispatch costs
            # more than their entire update
            use_kernel = (view is not None and p.size >= (1 << 16)
                          and rows % 8 == 0)
            fn = _fused_leaf_update if use_kernel else _xla_leaf_update
            np_, nm, nv = fn(p, g, m, v, scalars)
            new_p.append(np_)
            new_m.append(nm)
            new_v.append(nv)
        return (tdef.unflatten(new_p),
                FusedAdamWState(count=count, mu=tdef.unflatten(new_m),
                                nu=tdef.unflatten(new_v)),
                gnorm)


def _prod(dims) -> int:
    out = 1
    for d in dims:
        out *= d
    return out


def _global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves))
