"""Fused TPU ops (Pallas kernels) with dense-jnp correctness oracles.

The compute-kernel layer the reference never needed (it shipped no compute —
SURVEY.md §2): flash attention and fused norms sized for MXU/VMEM, running
in interpret mode on non-TPU backends for tests.
"""

from tony_tpu.ops.attention import flash_attention, reference_attention
from tony_tpu.ops.norms import (
    layer_norm,
    layer_norm_reference,
    rms_norm,
    rms_norm_reference,
)
from tony_tpu.ops.optim import FusedAdamW

__all__ = [
    "flash_attention",
    "FusedAdamW",
    "layer_norm",
    "layer_norm_reference",
    "reference_attention",
    "rms_norm",
    "rms_norm_reference",
]
