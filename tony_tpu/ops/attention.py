"""Flash attention: fused blockwise attention as Pallas TPU kernels.

Green-field for the TPU build — the reference delegates all compute to user
TF/PyTorch code (SURVEY.md §2.3); here the hot op the MXU lives on is a
first-class framework kernel. Design follows the flash-attention recipe on
the TPU memory hierarchy: Q/K/V tiles stream HBM→VMEM once, scores never
materialize in HBM, the online softmax keeps f32 running max/sum in VMEM
scratch across the innermost (kv) grid dimension, and the MXU sees only
[block_q, d] × [d, block_k] matmuls with ``preferred_element_type=f32``.

Two measured-on-v5e refinements over the textbook kernel (the per-grid-step
cost on this hardware is ~2-4µs, so step count matters as much as FLOPs):

- **Head grouping** (``block_h``): each grid step processes ``block_h``
  batch-heads (an in-kernel unrolled loop of 2-D matmuls), cutting the grid
  from ``b·h × nq × nk`` to ``b·h/block_h × nq × nk`` steps. At LM shapes
  (head_dim 64, seq 1k) the per-head blocks are far below MXU-saturating
  sizes, so amortizing the fixed step cost dominates.
- **GQA-native K/V** (round 3): when K/V carry fewer heads than Q
  (grouped-query attention), the kernels take them UNEXPANDED. Queries are
  laid out ``[b·h_kv, rep·sq, d]`` — each kv head's ``rep`` query heads
  form contiguous row bands sharing that head's K/V blocks in-kernel — and
  the causal mask uses the position within the band (``qi mod sq/bq``).
  K/V HBM traffic drops by h/h_kv and the ``jnp.repeat`` materialization
  disappears; dK/dV need no extra handling (the per-q-block partial sum
  already reduces across the bands).
- **Shared causal mask**: the block's position mask is an iota+compare
  computed once per grid step and reused by every head in the group, and
  kv-blocks entirely above the diagonal are skipped, so the VPU cost of
  masking amortizes to ~1 op/element instead of ~4.

Round-4 refinements, each measured on one v5e with xprof device time:

- **Base-2 online softmax**: ``scale·log2e`` folds into Q once outside the
  kernels; the kernels call ``exp2`` (VPU ``exp`` is exp2 plus a
  multiply) and convert lse to natural log only at finalize. Backward
  picks up a single ln2 on the [*, d]-shaped outputs.
- **Skip-block DMA elision**: causal index maps clamp the K/V (or q-side)
  block coordinate for above/below-diagonal skipped steps, so the
  pipeline never fetches blocks the kernel won't read.
- **Narrow-q × wide-kv blocks** (256×1024 fwd, 128×512 bwd): the
  [block_q, block_k] f32 score intermediates are the kernel-stack VMEM
  budget; shrinking block_q 4× is what affords kv blocks past 256 and
  with them fewer grid steps and less K/V re-fetch.

Backward recomputes scores (no O(S²) residuals) in a single fused pass by
default, on a KV-MAJOR grid: dK/dV accumulate in f32 VMEM scratch across
the inner q sweep (written once per kv block — no partials), and only the
per-kv-block dQ contributions ([nk, b·h, S, D], input dtype) are summed
by XLA outside — one score/exp recompute instead of the classic two-pass
split's two, which is what matters in this VPU-bound regime, and half the
partial-tensor traffic of the previous q-major layout. When the partials
would exceed the ``_FUSED_PARTIALS_BYTES`` budget (their HBM footprint
scales with nk), the backward falls back to the two-pass split: one pass
gridded over q-blocks accumulating dQ, one over kv-blocks accumulating
dK/dV. Wired together with ``jax.custom_vjp``.

On non-TPU backends (the 8-device CPU test mesh) the same kernels run in
Pallas interpret mode — bit-accurate, slow — or callers use
:func:`reference_attention`. Layouts are [batch, seq, heads, head_dim] at
the API, [batch·heads, seq, head_dim] inside; the layout
:mod:`tony_tpu.parallel.ring_attention` chunks over ``cp`` — this kernel is
the intra-chunk compute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1.0e30
_LANES = 128
# The online softmax runs in BASE-2 (flash-2-style transcendental
# thinning): `scale · log2(e)` is folded into Q once outside the kernels
# (a [*, d] multiply amortized over every kv block, instead of the
# per-block [bq, bk] `s * scale`), the kernels call `exp2` directly
# (VPU `exp` is exp2 plus an x·log2e multiply — dropped), and lse
# converts back to natural log only at finalize. Backward picks up a
# single ln2 factor on the score gradient (∂2^x/∂x = ln2·2^x), applied
# to the [*, d]-shaped dq/dk outputs rather than the score matrix.
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_group(bh: int, block_h: int) -> int:
    """Heads-per-grid-step. Must divide batch·heads, and — because the 2-D
    [g, bq] lse blocks hit Mosaic's (8, 128)-divisibility rule on the
    second-minor dim — must be a multiple of 8. Callers pad bh to a
    multiple of 8 first (:func:`flash_attention`), so a multiple-of-8
    divisor always exists."""
    best = 8
    for g in range(8, bh + 1, 8):
        if bh % g == 0 and g <= max(block_h, 8):
            best = g
    return best


def _causal_mask(qi, ki, bq: int, bk: int, window: int | None = None):
    """[bq, bk] bool mask for the (qi, ki) block — computed once per grid
    step and shared by all heads in the group. ``qi`` is the BAND-relative
    q-block index (callers take program_id(..) mod blocks-per-band; for
    plain MHA the band is the whole sequence and the mod is identity).
    ``window`` adds the sliding-window bound: query attends only the
    ``window`` most recent positions (qpos - kpos < window)."""
    qpos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = qpos >= kpos
    if window is not None:
        mask = jnp.logical_and(mask, qpos - kpos < window)
    return mask


def _block_work(qi, ki, bq: int, bk: int, window: int | None):
    """Whether block (qi, ki) holds ANY attended (q, k) pair: below-or-on
    the diagonal, and — with a sliding window — not entirely older than
    the window (youngest k in the block within ``window`` of the oldest
    q)."""
    work = (qi + 1) * bq > ki * bk
    if window is not None:
        work = jnp.logical_and(work,
                               qi * bq - ((ki + 1) * bk - 1) < window)
    return work


def _causal_dispatch(qi, ki, bq: int, bk: int, accumulate, on_skip=None,
                     window: int | None = None):
    """Causal (+ sliding-window) block triage, shared by every kernel:
    blocks with no attended pair — entirely above the diagonal, or (with
    ``window``) entirely older than the window — are skipped (``on_skip``
    runs if given — e.g. zeroing partial outputs); blocks whose every
    pair is attended run ``accumulate(False)`` (no per-element
    compare/select — measurable in these VPU-bound kernels, increasingly
    so at long sequence where such blocks dominate); boundary-crossing
    blocks run ``accumulate(True)``."""
    work = _block_work(qi, ki, bq, bk, window)
    unmasked = qi * bq >= (ki + 1) * bk - 1
    if window is not None:
        unmasked = jnp.logical_and(
            unmasked, (qi + 1) * bq - 1 - ki * bk < window)

    @pl.when(jnp.logical_and(work, unmasked))
    def _():
        accumulate(False)

    @pl.when(jnp.logical_and(work, jnp.logical_not(unmasked)))
    def _():
        accumulate(True)

    if on_skip is not None:
        @pl.when(jnp.logical_not(work))
        def _():
            on_skip()


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, ml_scr, acc_scr,
                *, causal: bool, g: int, bq: int, bk: int,
                nk: int, band_nq: int, window: int | None):
    # Q arrives PRE-SCALED by scale·log2e (:func:`_prep_flat`), so the
    # raw MXU dot is already the base-2 score and the kernel never
    # touches a [bq, bk] scale multiply; all max/sum bookkeeping below
    # is in the exp2 domain, converted to natural lse only at finalize.
    qi = pl.program_id(1) % band_nq     # GQA band-relative (identity: MHA)
    ki = pl.program_id(2)
    # ml_scr packs the running max (lane 0) and running sum (lane 1) into
    # one [g, bq, _LANES] buffer — each lives in its own 128-lane tile
    # anyway, so separate buffers would double the VMEM footprint.

    @pl.when(ki == 0)
    def _init():
        ml_scr[:] = jnp.full_like(ml_scr, _NEG_INF)

    def _accumulate(masked: bool):
        mask = _causal_mask(qi, ki, bq, bk, window) if masked else None
        for gi in range(g):
            q = q_ref[gi]                              # [bq, d], pre-scaled
            k = k_ref[gi]                              # [bk, d]
            v = v_ref[gi]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)    # [bq, bk], base-2
            if masked:
                s = jnp.where(mask, s, _NEG_INF)
            m_prev = ml_scr[gi, :, 0:1]                # [bq, 1]
            l_prev = ml_scr[gi, :, 1:2]
            first = m_prev <= _NEG_INF                 # nothing seen yet
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp2(s - m_new)                    # [bq, bk]
            corr = jnp.where(first, 0.0, jnp.exp2(m_prev - m_new))  # [bq, 1]
            l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
            if nk == 1 and not (causal and bq < bk):
                # single kv block: the accumulator rescale is dead code
                acc_scr[gi] = jax.lax.dot(
                    p.astype(v.dtype), v, preferred_element_type=jnp.float32)
            else:
                acc = jnp.where(first, 0.0, acc_scr[gi])
                acc_scr[gi] = acc * corr + jax.lax.dot(
                    p.astype(v.dtype), v, preferred_element_type=jnp.float32)
            ml_scr[gi, :, 0:1] = m_new
            ml_scr[gi, :, 1:2] = l_new

    if causal:
        _causal_dispatch(qi, ki, bq, bk, _accumulate, window=window)
    else:
        _accumulate(False)

    @pl.when(ki == nk - 1)
    def _finalize():
        for gi in range(g):
            m = ml_scr[gi, :, 0:1]
            l = ml_scr[gi, :, 1:2]
            o_ref[gi] = (acc_scr[gi] / jnp.maximum(l, 1e-30)).astype(
                o_ref.dtype)
            # natural-log lse: ln(2^m · l) = ln2 · (m + log2 l)
            lse_ref[gi] = (_LN2 * (m + jnp.log2(jnp.maximum(l, 1e-30))))[:, 0]


def _kv_index_map(causal: bool, bq: int, bk: int, band_nq: int,
                  window: int | None = None):
    """K/V block index map for q-major grids ``(b, qi, ki)``. For causal
    kernels the ki coordinate is CLAMPED to the last diagonal-touching
    block of the (band-relative) q row: skipped above-diagonal steps then
    repeat the previous step's block index, and the Pallas pipeline elides
    the HBM→VMEM copy for an unchanged index — at long sequence nearly
    half the K/V DMA traffic was being fetched for blocks the kernel
    never reads. A sliding ``window`` clamps from BELOW too: kv blocks
    entirely older than the window repeat the first in-window block's
    index, so their DMA is elided the same way — what makes windowed
    cost scale with the window, not the sequence."""
    if not causal:
        return lambda b, i, j: (b, j, 0)

    def _map(b, i, j):
        rel = i % band_nq
        last = ((rel + 1) * bq - 1) // bk
        if window is not None:
            first = jnp.maximum(rel * bq - window + 1, 0) // bk
            return (b, jnp.clip(j, first, last), 0)
        return (b, jnp.minimum(j, last), 0)

    return _map


def _flash_forward(q, k, v, *, causal, g, bq, bk, band, window=None):
    bh, sq, d = q.shape                 # sq = rep·band under GQA
    sk = k.shape[1]
    nq, nk = _cdiv(sq, bq), _cdiv(sk, bk)
    kernel = functools.partial(_fwd_kernel, causal=causal,
                               g=g, bq=bq, bk=bk, nk=nk,
                               band_nq=_cdiv(band, bq), window=window)
    kv_map = _kv_index_map(causal, bq, bk, _cdiv(band, bq), window)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh // g, nq, nk),
        in_specs=[
            pl.BlockSpec((g, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((g, bk, d), kv_map),
            pl.BlockSpec((g, bk, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((g, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((g, bq), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, bq, _LANES), jnp.float32),   # max (l0) + sum (l1)
            pltpu.VMEM((g, bq, d), jnp.float32),        # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# Backward, fused single pass (default): KV-MAJOR grid (bh/g, nk, nq) —
# ki outer, qi inner. dK/dV accumulate in f32 VMEM scratch across the qi
# sweep and are written ONCE per kv block (no dK/dV partials at all); the
# only partial tensor is per-kv-block dQ contributions [nk, bh, sq, d],
# summed by XLA outside. Compared to the round-2/3 q-major layout (which
# wrote TWO partial tensors, dK and dV), this halves partial HBM traffic
# and replaces two XLA reduces with one. One compiled body (mask applied
# on every active block — measured free next to exp2) keeps Mosaic's
# kernel stack small enough for 512-wide kv blocks; 128-row q blocks
# shrink the [bq, bk] f32 intermediates 4×, which is what buys the wide
# kv blocks under the ~16 MB VMEM limit. Measured (device-time via xprof,
# one v5e, seq 8k b4): 14.2 ms vs 17.1 ms for the q-major layout (1.21×);
# seq 1k b32: 3.32 vs 3.78 ms (1.14×). This recomputes scores/exp ONCE
# per backward instead of the two-pass split's twice, which matters
# because the kernel is VPU-bound (softmax ops, not MXU FLOPs, set the
# wall-clock at LM head dims). delta = rowsum(dO·O) is one fused XLA
# pass outside, fed (like lse) as 2-D [g, bq] blocks — no [.., _LANES]
# broadcasts ever touch HBM.
# ---------------------------------------------------------------------------

# Partial-tensor budget gating the fused backward (the dQ partials are
# nk × the q tensor size). Overridable: TONY_FLASH_FUSED_PARTIALS_MB.
# Measured on one v5e (bf16, 8 heads, d64, xprof device time): with the
# kv-major layout fused beats two-pass 14.2 vs 17.1 ms at seq 8k b4
# (512 MB partials) and 26.6 vs 32.6 ms at seq 16k b2 (1 GB partials) —
# the default covers both; raise further when HBM has headroom. Set 0
# to force two-pass: the fused path stores dQ partials in bf16 (error
# ~ √nk·eps_bf16), while two-pass accumulates dQ in f32 VMEM — the
# knob is the precision escape hatch.
import os as _os

_FUSED_PARTIALS_BYTES = int(_os.environ.get(
    "TONY_FLASH_FUSED_PARTIALS_MB", "1024")) * 1024 * 1024

# Backward block shape on real TPUs (interpret mode keeps caller blocks
# so tiny CPU test shapes stay bit-testable): 128-row q blocks × 512-wide
# kv blocks won the v5e sweep — [128, 512] f32 stack intermediates are
# small enough for the single-body kernel to fit VMEM with headroom.
_BWD_BQ = 128
_BWD_BK = 512


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *refs, causal: bool, g: int, bq: int, bk: int,
                      nq: int, has_dlse: bool, band_nq: int,
                      window: int | None):
    # refs = ([dlse_ref,] dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr): the
    # dlse input exists only for the with-lse entry point, so the hot
    # plain-attention path compiles the exact same kernel.
    if has_dlse:
        dlse_ref, dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        dlse_ref = None
        dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    ki = pl.program_id(1)
    qi_g = pl.program_id(2)             # inner: restarts per kv block
    qi = qi_g % band_nq                 # GQA band-relative (identity: MHA)

    @pl.when(qi_g == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate():
        # single body: the causal mask runs on every active block (its
        # iota+compare is in the noise next to exp2), which keeps one
        # copy of the [bq, bk] f32 intermediates on the kernel stack —
        # the VMEM room that pays for 512-wide kv blocks.
        mask = _causal_mask(qi, ki, bq, bk, window) if causal else None
        for gi in range(g):
            q = q_ref[gi]                               # [bq, d], pre-scaled
            k = k_ref[gi]                               # [bk, d]
            v = v_ref[gi]
            do = do_ref[gi]
            lse2 = lse_ref[gi][:, None]                 # [bq, 1], base-2
            # d(lse) enters the score gradient additively:
            # ds = p · (dp - delta + dlse); delta_eff folds it in
            delta = delta_ref[gi][:, None]              # [bq, 1]
            if has_dlse:
                delta = delta - dlse_ref[gi][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bq, bk], base-2
            if causal:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp2(s - lse2)                      # [bq, bk]
            dv_scr[gi] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bk, d]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bq, bk]
            # base-2 score grad is ln2·p·(dp - delta); the ln2 lands on
            # the [*, d]-shaped dk/dq outputs, never the score matrix
            ds = p * (dp - delta)                       # [bq, bk]
            dk_scr[gi] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bk, d]
            dqp_ref[0, gi] = (_LN2 * jax.lax.dot(
                ds.astype(k.dtype), k,
                preferred_element_type=jnp.float32)).astype(dqp_ref.dtype)

    if causal:
        work = _block_work(qi, ki, bq, bk, window)

        @pl.when(work)
        def _():
            _accumulate()

        @pl.when(jnp.logical_not(work))
        def _():
            # blocks with no attended pair (above the diagonal, or older
            # than the sliding window) contribute nothing, but their dq
            # partial blocks still exist and must be zeroed
            dqp_ref[:] = jnp.zeros_like(dqp_ref)
    else:
        _accumulate()

    @pl.when(qi_g == nq - 1)
    def _finalize():
        dk_ref[:] = (_LN2 * dk_scr[:]).astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_q_maps(causal: bool, bq: int, bk: int, band_nq: int,
                window: int | None = None):
    """Index maps for q-side operands on the kv-major grid ``(b, ki, qi)``.
    For causal kernels the leading (band-relative) q blocks of each kv
    sweep sit above the diagonal and are skipped — clamp them to the
    first diagonal-touching block so the pipeline doesn't DMA blocks the
    kernel never reads (mirror of :func:`_kv_index_map`). With a sliding
    ``window``, trailing q blocks entirely NEWER than window-past-this-kv
    are skipped too — clamp from above symmetrically."""
    if not causal:
        return (lambda b, j, i: (b, i, 0)), (lambda b, j, i: (b, i))

    def _clamp(j, i):
        rel = i % band_nq
        first = (j * bk) // bq
        if window is not None:
            last = jnp.minimum((j + 1) * bk - 1 + window - 1, band_nq
                               * bq - 1) // bq
            return i - rel + jnp.clip(rel, first, jnp.maximum(last, first))
        return i - rel + jnp.maximum(rel, first)

    return (lambda b, j, i: (b, _clamp(j, i), 0),
            lambda b, j, i: (b, _clamp(j, i)))


def _flash_backward_fused(q, k, v, o, lse, do, dlse, *, causal, g,
                          bq, bk, band, window=None):
    bh, sq, d = q.shape
    sk = k.shape[1]
    has_dlse = dlse is not None
    # Swap to the measured-best backward blocks when they tile the
    # shapes (always true at the power-of-two LM lengths); interpret
    # mode keeps caller blocks so tiny CPU test shapes exercise the
    # same kernel. The head group is clamped independently of the
    # forward's: the backward holds 2× f32 kv-block scratch per head,
    # so the forward's g=16 short-kv choice blows its VMEM (any g=16
    # implies 8 | bh, so the clamp always divides).
    if not _interpret():
        g = min(g, 8)
        if sq % _BWD_BQ == 0 and band % _BWD_BQ == 0:
            bq = _BWD_BQ
        if sk % _BWD_BK == 0:
            bk = _BWD_BK
        elif bk > 256 and sk % 256 == 0:
            bk = 256
    nq, nk = _cdiv(sq, bq), _cdiv(sk, bk)
    band_nq = _cdiv(band, bq)
    # ds = p · (dp - delta + dlse): delta = rowsum(dO·O) is one fused XLA
    # elementwise+reduce pass; base-2 lse feeds the exp2-domain kernel.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                            # [bh, sq]
    lse2 = lse * _LOG2E
    q_map, q_map2 = _bwd_q_maps(causal, bq, bk, band_nq, window)
    in_specs = [
        pl.BlockSpec((g, bq, d), q_map),
        pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((g, bq, d), q_map),
        pl.BlockSpec((g, bq), q_map2),
        pl.BlockSpec((g, bq), q_map2),
    ]
    operands = [q, k, v, do, lse2, delta]
    if has_dlse:
        in_specs.append(pl.BlockSpec((g, bq), q_map2))
        operands.append(dlse)
    dqp, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, causal=causal,
                          g=g, bq=bq, bk=bk, nq=nq, has_dlse=has_dlse,
                          band_nq=band_nq, window=window),
        grid=(bh // g, nk, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, g, bq, d), lambda b, j, i: (j, b, i, 0)),
            pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            # dQ partials are stored at input precision, not f32: each
            # element is a complete f32 MXU accumulation over the kv-block
            # columns rounded ONCE, and the partials are summed in f32
            # below. Worst-case error ~ √nk · eps_bf16 (covered by
            # test_gradients_bfloat16_long_seq) — for half the partial
            # HBM traffic.
            jax.ShapeDtypeStruct((nk, bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((g, bk, d), jnp.float32),
                        pltpu.VMEM((g, bk, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*operands)
    if nk == 1:
        return dqp[0], dk, dv
    dq = dqp.astype(jnp.float32).sum(0).astype(q.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Backward, two-pass fallback for long sequences: dQ pass (grid over q
# blocks, inner loop over kv blocks)
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, causal: bool, g: int, bq: int,
               bk: int, nk: int, band_nq: int, window: int | None):
    qi = pl.program_id(1) % band_nq     # GQA band-relative (identity: MHA)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _accumulate(masked: bool):
        mask = _causal_mask(qi, ki, bq, bk, window) if masked else None
        for gi in range(g):
            q = q_ref[gi]                               # [bq, d], pre-scaled
            k = k_ref[gi]
            v = v_ref[gi]
            do = do_ref[gi]                             # [bq, d]
            lse2 = lse_ref[gi][:, None]                 # [bq, 1], base-2
            delta = delta_ref[gi][:, None]              # [bq, 1]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # base-2
            if masked:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp2(s - lse2)                      # [bq, bk]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bq, bk]
            ds = p * (dp - delta)
            dq_scr[gi] += jax.lax.dot(ds.astype(k.dtype), k,
                                      preferred_element_type=jnp.float32)

    if causal:
        _causal_dispatch(qi, ki, bq, bk, _accumulate, window=window)
    else:
        _accumulate(False)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[:] = (_LN2 * dq_scr[:]).astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# Backward: dK/dV pass (grid over kv blocks, inner loop over q blocks)
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *,
                causal: bool, g: int, bq: int, bk: int, nq: int,
                band_nq: int, window: int | None):
    ki = pl.program_id(1)
    qi_g = pl.program_id(2)             # global: init/finalize sequencing
    qi = qi_g % band_nq                 # GQA band-relative: causal triage

    @pl.when(qi_g == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate(masked: bool):
        mask = _causal_mask(qi, ki, bq, bk, window) if masked else None
        for gi in range(g):
            q = q_ref[gi]                               # [bq, d], pre-scaled
            k = k_ref[gi]                               # [bk, d]
            v = v_ref[gi]
            do = do_ref[gi]
            lse2 = lse_ref[gi][:, None]                 # base-2
            delta = delta_ref[gi][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bq, bk], base-2
            if masked:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp2(s - lse2)                      # [bq, bk]
            dv_scr[gi] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bk, d]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bq, bk]
            ds = p * (dp - delta)                       # [bq, bk]
            dk_scr[gi] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # [bk, d]

    if causal:
        _causal_dispatch(qi, ki, bq, bk, _accumulate, window=window)
    else:
        _accumulate(False)

    @pl.when(qi_g == nq - 1)
    def _finalize():
        dk_ref[:] = (_LN2 * dk_scr[:]).astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, dlse=None, *, causal, g,
                    bq, bk, band, window=None):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = _cdiv(sq, bq), _cdiv(sk, bk)
    # dQ partials are [nk, bh, sq, d] at the blocks the fused path will
    # actually pick — mirror its clamp chain exactly.
    bk_eff = bk
    if not _interpret():
        if sk % _BWD_BK == 0:
            bk_eff = _BWD_BK
        elif bk > 256 and sk % 256 == 0:
            bk_eff = 256
    partial_bytes = _cdiv(sk, bk_eff) * bh * sq * d * q.dtype.itemsize
    if partial_bytes <= _FUSED_PARTIALS_BYTES:
        return _flash_backward_fused(q, k, v, o, lse, do, dlse,
                                     causal=causal, g=g, bq=bq, bk=bk,
                                     band=band, window=window)
    # Mosaic allocates kernel stack for BOTH _causal_dispatch bodies, so the
    # [bq, bk] f32 intermediates count twice; 256-wide blocks keep the
    # two-pass kernels inside the ~16 MB VMEM budget (long sequences have
    # hundreds of grid steps either way). Same independent head-group
    # clamp as the fused path (the forward may have picked g=16).
    if not _interpret():
        g = min(g, 8)
    if bq > 256 and sq % 256 == 0 and band % 256 == 0:
        bq = 256
        nq = _cdiv(sq, bq)
    if bk > 256 and sk % 256 == 0:
        bk = 256
        nk = _cdiv(sk, bk)
    # ds = p · (dp - delta + dlse): fold the lse cotangent into delta;
    # base-2 lse for the exp2-domain kernels. Both ride as 2-D [g, bq]
    # blocks — no [.., _LANES] HBM broadcasts.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                            # [bh, sq]
    if dlse is not None:
        delta = delta - dlse
    lse2 = lse * _LOG2E
    kv_map = _kv_index_map(causal, bq, bk, _cdiv(band, bq), window)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, g=g,
                          bq=bq, bk=bk, nk=nk, band_nq=_cdiv(band, bq),
                          window=window),
        grid=(bh // g, nq, nk),
        in_specs=[
            pl.BlockSpec((g, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((g, bk, d), kv_map),
            pl.BlockSpec((g, bk, d), kv_map),
            pl.BlockSpec((g, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((g, bq), lambda b, i, j: (b, i)),
            pl.BlockSpec((g, bq), lambda b, i, j: (b, i)),
        ],
        out_specs=pl.BlockSpec((g, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((g, bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse2, delta)

    band_nq = _cdiv(band, bq)
    q_map, q_map2 = _bwd_q_maps(causal, bq, bk, band_nq, window)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, g=g,
                          bq=bq, bk=bk, nq=nq, band_nq=band_nq,
                          window=window),
        grid=(bh // g, nk, nq),
        in_specs=[
            pl.BlockSpec((g, bq, d), q_map),
            pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((g, bq, d), q_map),
            pl.BlockSpec((g, bq), q_map2),
            pl.BlockSpec((g, bq), q_map2),
        ],
        out_specs=[
            pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((g, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, bk, d), jnp.float32),
            pltpu.VMEM((g, bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse2, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention_bhsd(q, k, v, causal, g, bq, bk, band, window):
    # q arrives pre-scaled by scale·log2e (:func:`_prep_flat`); the fold
    # sits OUTSIDE this custom_vjp boundary, so plain AD of the multiply
    # routes the scale factor into dq for free.
    o, _ = _flash_forward(q, k, v, causal=causal, g=g, bq=bq,
                          bk=bk, band=band, window=window)
    return o


def _flash_fwd_rule(q, k, v, causal, g, bq, bk, band, window):
    o, lse = _flash_forward(q, k, v, causal=causal, g=g, bq=bq,
                            bk=bk, band=band, window=window)
    # checkpoint_name on the kernel OUTPUTS: under
    # remat_policy="attn" (save_only_these_names) the remat replay
    # fetches o/lse from the saved forward and DCE drops the flash
    # forward kernel from the recompute graph entirely — the backward
    # then re-runs only the cheap projections, not the O(S²) kernel.
    # Under other policies the names are inert.
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, g, bq, bk, band, window, residuals, grad):
    q, k, v, o, lse = residuals
    return _flash_backward(q, k, v, o, lse, grad, causal=causal,
                           g=g, bq=bq, bk=bk, band=band, window=window)


_flash_attention_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention_lse_bhsd(q, k, v, causal, g, bq, bk, band, window):
    """(o, lse) variant with lse as a DIFFERENTIATED output — what
    cross-chunk softmax merging (ring attention) needs: the merge weights
    are exp(lse_chunk - lse_total), so d(lse) must flow back into the
    score gradient (ds gains a +p·dlse term, folded into delta)."""
    return _flash_forward(q, k, v, causal=causal, g=g, bq=bq,
                          bk=bk, band=band, window=window)


def _flash_lse_fwd_rule(q, k, v, causal, g, bq, bk, band, window):
    o, lse = _flash_forward(q, k, v, causal=causal, g=g, bq=bq,
                            bk=bk, band=band, window=window)
    o = checkpoint_name(o, "flash_out")       # see _flash_fwd_rule
    lse = checkpoint_name(lse, "flash_lse")
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_bwd_rule(causal, g, bq, bk, band, window, residuals,
                        grads):
    q, k, v, o, lse = residuals
    do, dlse = grads
    return _flash_backward(q, k, v, o, lse, do,
                           dlse.astype(jnp.float32),
                           causal=causal, g=g, bq=bq, bk=bk, band=band,
                           window=window)


_flash_attention_lse_bhsd.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


def _resolve_window(window, causal: bool, sq: int) -> int | None:
    """Validate/normalize the sliding-window size: None or >= sq means
    full causal attention (no window term compiled into the kernels);
    windowed non-causal attention is undefined here (the window is
    anchored on the causal diagonal)."""
    if window is None:
        return None
    if not causal:
        raise ValueError("sliding-window attention requires causal=True "
                         "(the window is anchored on the diagonal)")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return None if window >= sq else int(window)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: float | None = None,
                    block_q: int = 256, block_k: int = 1024,
                    block_h: int = 4, window: int | None = None):
    """Fused attention over [batch, seq, heads, head_dim] inputs.

    K/V may carry FEWER heads than Q (grouped-query attention, h_kv | h):
    they are consumed unexpanded — query head i reads kv head
    i // (h/h_kv), the same blocked layout as
    ``models.transformer.expand_kv`` — so GQA cuts the kernels' K/V HBM
    traffic by h/h_kv instead of materializing a repeated tensor.

    Block sizes are clamped to the input shapes (tiny test shapes).
    Defaults were swept on a v5e chip at LM shapes (seq 1k-8k, head_dim
    64): narrow q blocks × wide kv blocks (256×1024 forward, 128×512
    backward) won — the [block_q, block_k] f32 score intermediates are
    the VMEM budget, and shrinking block_q is what affords wide kv
    blocks, fewer grid steps, and less K/V re-fetch per output row.
    ``block_h`` is a hint for heads-per-grid-step, resolved by
    :func:`_pick_group` (a multiple of 8 dividing batch·heads, or all of
    them); grouping amortizes the fixed ~2-4 µs per-grid-step cost,
    bounded by VMEM — the binding term is the single compiled body's
    [block_q, block_k] f32 score intermediates times the g-scaled
    input/output/scratch blocks. Differentiable via the fused kv-major
    flash backward (two-pass kernels for long sequences).

    ``window`` enables SLIDING-WINDOW attention (causal only): each
    query attends its ``window`` most recent positions. Blocks entirely
    older than the window are triaged out exactly like above-diagonal
    blocks — skipped compute AND elided DMA (index maps clamp from
    below) — so fwd+bwd cost scales with ``seq × window``, not seq²;
    the boundary blocks take the masked body with the window bound
    folded into the same [bq, bk] compare the causal mask already pays.
    """
    if _sub_tile(q, block_q):
        return reference_attention(q, k, v, causal=causal, scale=scale,
                                   window=window)
    window = _resolve_window(window, causal, q.shape[1])
    qf, kf, vf, g, bq, bk, band = _prep_flat(q, k, v, scale, block_q,
                                             block_k, block_h)
    b, sq, h, d = q.shape
    hk = k.shape[2]
    o = _flash_attention_bhsd(qf, kf, vf, causal, g, bq, bk, band,
                              window)
    return (o[:b * hk].reshape(b, h, sq, d).transpose(0, 2, 1, 3))


def _sub_tile(q, block_q: int) -> bool:
    """True when the resolved q-block would be below the 128-lane tile on
    a REAL TPU — the 2-D [g, bq] lse layout makes bq the lane dim, and
    sub-128 lanes are an untested Mosaic regime (interpret mode — the CPU
    test path — keeps small blocks so the kernels stay bit-testable).
    Callers fall back to the dense arm, which has no tiling demands."""
    if _interpret():
        return False
    return min(block_q, q.shape[1]) % _LANES != 0


def _prep_flat(q, k, v, scale, block_q: int, block_k: int, block_h: int):
    """Shared entry prep: validate blocks, flatten [B,S,H,D] →
    [B·H_kv, (H/H_kv)·S, D] — under GQA each kv head's queries form
    contiguous row BANDS of length S sharing that head's K/V; plain MHA is
    the 1-band case — pad batch·kv-heads to a multiple of 8 (Mosaic needs
    the 2-D lse block's leading dim divisible by 8; zero heads give zero
    scores → uniform softmax over zero values → o = 0, finite lse, zero
    grads — callers slice the padding off), and resolve the head group.
    Q is scaled by ``scale · log2(e)`` HERE — one [*, d] multiply XLA
    fuses into the layout change — so the kernels' raw MXU dot is the
    base-2 score and no [bq, bk] scale multiply ever runs; the fold sits
    outside the custom_vjp, so AD routes the factor into dq.
    Returns the flat operands plus the band length S."""
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hk <= 0 or h % hk:
        raise ValueError(f"kv heads ({hk}) must divide query heads ({h})")
    rep = h // hk
    if sq % min(block_q, sq) or sk % min(block_k, sk):
        raise ValueError(f"seq lengths ({sq}, {sk}) must divide into blocks")
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if not _interpret() and bk == sk and bq < bk and sk % 256 == 0 \
            and sk >= 1024:
        # single-kv-block grids at wide bk lose the revolving-buffer
        # VMEM reuse and blow the ~16 MB budget by a hair (measured:
        # [256, 1024] at nk=1 is 68 KB over); two kv blocks fit.
        bk = sk // 2
    if not _interpret() and bk <= 512 and bq > 128 and sq % 128 == 0:
        # short-kv regime (the wide-kv choice above didn't engage): the
        # v5e sweep at seq 1k picked 128-row q blocks with a DOUBLE head
        # group (2.00 ms vs 2.44 for 256×512 g8, vs 2.08 for the old
        # 512×512 g8) — the narrow stack buys the bigger g, and g is
        # what amortizes per-step cost when kv blocks can't widen.
        bq = 128
        block_h = max(block_h, 16)
    scale = (d ** -0.5) if scale is None else scale
    # fold in f32 and round ONCE: casting the constant itself to bf16
    # would bake a systematic ~0.2% temperature error into every logit
    q = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    # [B,S,H,D] → [B,H,S,D] → group rep query heads per kv head into one
    # row dim (blocked head order: query head i ↔ kv head i // rep)
    qf = q.transpose(0, 2, 1, 3).reshape(b * hk, rep * sq, d)
    to_flat = lambda x: x.transpose(0, 2, 1, 3).reshape(b * hk, x.shape[1], d)
    kf, vf = to_flat(k), to_flat(v)
    bh = b * hk
    if bh % 8:
        pad = 8 * _cdiv(bh, 8) - bh
        qf, kf, vf = (jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
                      for x in (qf, kf, vf))
    g = _pick_group(qf.shape[0], block_h)
    return qf, kf, vf, g, bq, bk, sq


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             scale: float | None = None,
                             block_q: int = 256, block_k: int = 1024,
                             block_h: int = 4, window: int | None = None):
    """Like :func:`flash_attention` but also returns the row logsumexp
    ([batch, heads, seq], f32) as a DIFFERENTIATED output — the primitive
    for cross-chunk softmax merging (ring attention): merged
    results are ``o = Σ_c o_c · exp(lse_c - logaddexp_c lse_c)``, and the
    lse cotangent flows back into the score gradients. GQA K/V (fewer
    heads than Q) and sliding windows are supported exactly as in
    :func:`flash_attention`."""
    if _sub_tile(q, block_q):
        return _dense_with_lse(q, k, v, causal=causal, scale=scale,
                               window=window)
    window = _resolve_window(window, causal, q.shape[1])
    qf, kf, vf, g, bq, bk, band = _prep_flat(q, k, v, scale, block_q,
                                             block_k, block_h)
    b, sq, h, d = q.shape
    hk = k.shape[2]
    o, lse = _flash_attention_lse_bhsd(qf, kf, vf, causal, g, bq, bk,
                                       band, window)
    return (o[:b * hk].reshape(b, h, sq, d).transpose(0, 2, 1, 3),
            lse[:b * hk].reshape(b, h, sq))


def _dense_with_lse(q, k, v, *, causal: bool, scale: float | None,
                    window: int | None = None):
    """Dense (o, lse): the sub-tile fallback for the with-lse entry and
    the body of :func:`reference_attention` (plain jnp, so AD provides
    the dlse flow for free). GQA K/V (fewer heads than Q) is expanded —
    this is the oracle/CPU arm, where clarity beats the bandwidth saving
    the kernels exist for."""
    d = q.shape[-1]
    h, hk = q.shape[2], k.shape[2]
    window = _resolve_window(window, causal, q.shape[1])
    if h != hk:
        if hk <= 0 or h % hk:
            raise ValueError(f"kv heads ({hk}) must divide heads ({h})")
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = jnp.arange(q.shape[1])[:, None]
        kpos = jnp.arange(k.shape[1])[None, :]
        mask = qpos >= kpos
        if window is not None:
            mask = mask & (qpos - kpos < window)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return o.astype(q.dtype), lse


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: float | None = None,
                        window: int | None = None):
    """Dense O(S²) attention in plain jnp — the correctness oracle for
    the kernels and the fallback for odd shapes (GQA-aware, sliding-
    window-aware; see :func:`_dense_with_lse`, whose output this is)."""
    o, _ = _dense_with_lse(q, k, v, causal=causal, scale=scale,
                           window=window)
    return o
