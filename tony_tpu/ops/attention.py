"""Flash attention: fused blockwise attention as Pallas TPU kernels.

Green-field for the TPU build — the reference delegates all compute to user
TF/PyTorch code (SURVEY.md §2.3); here the hot op the MXU lives on is a
first-class framework kernel. Design follows the flash-attention recipe on
the TPU memory hierarchy: Q/K/V tiles stream HBM→VMEM once, scores never
materialize in HBM, the online softmax keeps f32 running max/sum in VMEM
scratch across the innermost (kv) grid dimension, and the MXU sees only
[block_q, d] × [d, block_k] matmuls with ``preferred_element_type=f32``.

Backward is the standard two-kernel split (recompute, no O(S²) residuals):
one pass gridded over q-blocks accumulating dQ, one over kv-blocks
accumulating dK/dV, both reusing the forward's logsumexp and the
delta = rowsum(dO·O) precomputation. Wired together with ``jax.custom_vjp``.

On non-TPU backends (the 8-device CPU test mesh) the same kernels run in
Pallas interpret mode — bit-accurate, slow — or callers use
:func:`reference_attention`. Layouts are [batch, heads, seq, head_dim]
(attention-major), the layout :mod:`tony_tpu.parallel.ring_attention` chunks
over ``cp``; this kernel is the intra-chunk compute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1.0e30
_LANES = 128


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, bq: int, bk: int, nk: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accumulate():
        q = q_ref[0]                                   # [bq, d]
        k = k_ref[0]                                   # [bk, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            qpos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                          # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                 # [bq, 1]
        l_new = l_scr[:, :1] * corr + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip fully-masked kv blocks (everything strictly above the diag)
        @pl.when((qi + 1) * bq > ki * bk)
        def _():
            _accumulate()
    else:
        _accumulate()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30)), lse_ref.shape[1:])


def _flash_forward(q, k, v, *, scale, causal, bq, bk):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = _cdiv(sq, bq), _cdiv(sk, bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running max
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running sum
            pltpu.VMEM((bq, d), jnp.float32),        # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# Backward: dQ pass (grid over q blocks, inner loop over kv blocks)
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale: float, causal: bool, bq: int, bk: int,
               nk: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]                                  # [bq, d]
        lse = lse_ref[0][:, :1]                         # [bq, 1]
        delta = delta_ref[0][:, :1]                     # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)                            # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, bk]
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32)

    if causal:
        @pl.when((qi + 1) * bq > ki * bk)
        def _():
            _accumulate()
    else:
        _accumulate()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# Backward: dK/dV pass (grid over kv blocks, inner loop over q blocks)
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool, bq: int, bk: int, nq: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate():
        q = q_ref[0]                                    # [bq, d]
        k = k_ref[0]                                    # [bk, d]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            qpos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)                            # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, bk]
        ds = p * (dp - delta) * scale                   # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bk, d]

    if causal:
        @pl.when((qi + 1) * bq > ki * bk)
        def _():
            _accumulate()
    else:
        _accumulate()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, *, scale, causal, bq, bk):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = _cdiv(sq, bq), _cdiv(sk, bk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                            # [bh, sq]
    lse_l = jnp.broadcast_to(lse[..., None], (bh, sq, _LANES))
    delta_l = jnp.broadcast_to(delta[..., None], (bh, sq, _LANES))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse_l, delta_l)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse_l, delta_l)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_bhsd(q, k, v, scale, causal, bq, bk):
    o, _ = _flash_forward(q, k, v, scale=scale, causal=causal, bq=bq, bk=bk)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, bq, bk):
    o, lse = _flash_forward(q, k, v, scale=scale, causal=causal, bq=bq, bk=bk)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(scale, causal, bq, bk, residuals, g):
    q, k, v, o, lse = residuals
    return _flash_backward(q, k, v, o, lse, g, scale=scale, causal=causal,
                           bq=bq, bk=bk)


_flash_attention_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: float | None = None,
                    block_q: int = 512, block_k: int = 1024):
    """Fused attention over [batch, seq, heads, head_dim] inputs.

    Block sizes are clamped to the sequence lengths (tiny test shapes).
    Defaults were swept on a v5e chip: 512×1024 runs ~2000× faster than
    128×128 (grid-step overhead dominates small blocks) and beats the XLA
    dense-softmax fusion at S=1024. Differentiable via the flash backward
    kernels.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sq % min(block_q, sq) or sk % min(block_k, sk):
        raise ValueError(f"seq lengths ({sq}, {sk}) must divide into blocks")
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    scale = (d ** -0.5) if scale is None else scale
    to_bhsd = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    o = _flash_attention_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                              scale, causal, bq, bk)
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: float | None = None):
    """Dense O(S²) attention in plain jnp — the correctness oracle for the
    kernels and the fallback for odd shapes."""
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
