"""Fused normalization ops: RMSNorm / LayerNorm.

Bandwidth-bound row reductions: the Pallas forward keeps each row tile in
VMEM for exactly one HBM read and one write, with f32 accumulation (the
bf16 params/activations path the models use). Backwards are plain-jnp
custom-VJP rules — elementwise math XLA fuses into the surrounding backward
graph anyway, so a hand kernel would only add dispatch overhead.

On non-TPU backends the kernels run in interpret mode (tests) — callers on
the hot CPU path should use the ``*_reference`` versions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[...] = (x * rms * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    inv = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    o_ref[...] = (xc * inv * w_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _row_call(kernel, x, *params, block_rows: int = 256):
    """Run a row-wise kernel over x reshaped to [rows, d]."""
    shape = x.shape
    d = shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    br = min(block_rows, rows)
    if rows % br:
        br = 1  # degenerate fallback for odd row counts
    out = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0))] +
                 [pl.BlockSpec((d,), lambda i: (0,))] * len(params),
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=_interpret(),
    )(x2, *params)
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------

def rms_norm_reference(x, w, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms * w.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, w, eps: float = 1e-6):
    """RMSNorm over the last dim (fused on TPU)."""
    return _row_call(functools.partial(_rms_kernel, eps=eps), x, w)


def _rms_fwd(x, w, eps):
    return rms_norm(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    xhat = xf * inv
    gw = gf * wf
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(w.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def layer_norm_reference(x, w, b, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    inv = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    return (xc * inv * w.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x, w, b, eps: float = 1e-6):
    """LayerNorm over the last dim (fused on TPU)."""
    return _row_call(functools.partial(_ln_kernel, eps=eps), x, w, b)


def _ln_fwd(x, w, b, eps):
    return layer_norm(x, w, b, eps), (x, w)


def _ln_bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    inv = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    xhat = xc * inv
    gw = gf * wf
    dx = inv * (gw - jnp.mean(gw, axis=-1, keepdims=True)
                - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    reduce_axes = tuple(range(x.ndim - 1))
    dw = jnp.sum(gf * xhat, axis=reduce_axes)
    db = jnp.sum(gf, axis=reduce_axes)
    return dx.astype(x.dtype), dw.astype(w.dtype), db.astype(gf.dtype)


layer_norm.defvjp(_ln_fwd, _ln_bwd)
